"""Cross-framework parity harness: the ACTUAL reference (torch, mounted
read-only at /root/reference) vs msrflute_tpu on identical synthetic user
blobs, identical initial weights, matched hyperparameters.

Round-by-round val loss/acc trajectories are compared per task and written
to PARITY.json.  This is the strongest accuracy-parity evidence obtainable
with zero egress (real datasets unfetchable): both frameworks run their own
full federated stacks — reference thread-mode single process
(``core/federated.py:634-676``), msrflute_tpu its jitted SPMD round — and
must produce the same numbers.

Design notes:
- The reference runs from a symlink scratch tree (its plugin loaders
  resolve ``experiments/<task>`` against cwd; /root/reference is read-only
  so adapters are injected via the tree, never written there).
- Adapter tasks (tools/parity/adapters/) re-export the reference's own
  model/dataloader classes, adding only json-path loading.
- Identical init: one numpy weight set is written as a torch state_dict
  for the reference (``model_config.pretrained_model_path``,
  ``utils/utils.py:486-494``) and as a params-pytree msgpack for
  msrflute_tpu (same config key).  Layout conversions: torch Linear
  [out,in] -> flax kernel [in,out]; torch Conv [out,in,kh,kw] -> flax
  [kh,kw,in,out]; the CNN's flatten bridge permutes CHW->HWC flat order.
- Determinism: full participation (K == pool), one local epoch, one batch
  per client (batch_size >= samples/user), plain SGD both sides -> the
  trajectory is RNG-free except CNN dropout (LR is compared strictly;
  CNN by round-0 exactness + both-learned + matched endpoints, since
  dropout RNG time-offsets make pointwise mid-trajectory bands
  meaningless during steep descent).
- Images are stored pre-transposed for the reference (its __getitem__
  applies ``.T``, ``experiments/cv_lr_mnist/dataloaders/dataset.py:34``)
  and un-transposed for msrflute_tpu, so both models see the same tensors.

Usage: python tools/parity/run_parity.py [--tasks lr,cnn] [--rounds 20]

Extension modes (VERDICT r3 item 2) ride the deterministic LR base and are
selected through the same --tasks flag: ``dga`` (softmax weighting),
``dga_quant`` (+8-bit gradient quantization), ``dp_clip`` (clip-only local
DP, eps<0), ``dp_tiny_noise`` (the full eps>0 dance at vanishing sigma +
global DP at sigma=0 — near-deterministic, so semantic divergence shows as
drift), ``dp_envelope`` (real noise, statistical-envelope criteria).
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
REFERENCE = "/root/reference"
ADAPTERS = os.path.join(REPO, "tools", "parity", "adapters")

#: sequential reference launches need distinct rendezvous ports (TIME_WAIT)
_REF_RUN_SEQ = 0


# ----------------------------------------------------------------------
# synthetic blobs
# ----------------------------------------------------------------------
def gen_blob(rng, users, samples, shape, classes, sep=2.0, means=None):
    """Class-structured gaussian data: learnable but not trivial.

    Pass the same ``means`` for train and val: a fresh draw per split
    would make validation distributionally unrelated to training and pin
    val accuracy at chance regardless of learning.  ``samples`` may be a
    per-user sequence — UNEVEN sizes make the sample-count aggregation
    weights load-bearing (equal users cancel any constant factor in the
    normalized aggregate)."""
    if means is None:
        means = rng.normal(size=(classes,) + shape).astype(np.float32)
    per_user = (list(samples) if isinstance(samples, (list, tuple))
                else [samples] * users)
    out = {"users": [], "num_samples": [], "user_data": {},
           "user_data_label": {}}
    for u in range(users):
        samples = per_user[u]
        y = rng.integers(0, classes, size=(samples,))
        x = (sep * means[y]
             + rng.normal(size=(samples,) + shape)).astype(np.float32)
        name = f"{u:04d}"
        out["users"].append(name)
        out["num_samples"].append(samples)
        out["user_data"][name] = {"x": x}
        out["user_data_label"][name] = y.astype(np.int64)
    return out


def write_blob(blob, path, transpose_images=False):
    def conv(x):
        x = np.asarray(x)
        if transpose_images and x.ndim == 3:  # [N, H, W] -> stored .T'd
            x = np.swapaxes(x, 1, 2)
        return x.tolist()

    js = {
        "users": blob["users"],
        "num_samples": blob["num_samples"],
        "user_data": {u: {"x": conv(d["x"])}
                      for u, d in blob["user_data"].items()},
        "user_data_label": {u: np.asarray(l).tolist()
                            for u, l in blob["user_data_label"].items()},
    }
    with open(path, "w") as fh:
        json.dump(js, fh)


def _markov_stream(rng, length, vocab, trans, noise):
    """One noisy-Markov token stream (ids 1..vocab-1): next id is
    ``trans[cur]`` with prob 1-noise, else uniform — the shared
    synthetic-language kernel of the lstm and gru blobs."""
    stream = np.empty(length, np.int64)
    stream[0] = rng.integers(1, vocab)
    for t in range(length - 1):
        stream[t + 1] = (rng.integers(1, vocab)
                         if rng.random() < noise
                         else trans[stream[t] - 1])
    return stream


def gen_lstm_blob(rng, users, samples, seq_len, vocab=90, trans=None,
                  noise=0.15):
    """Char sequences from a noisy deterministic next-char rule: with
    prob ``1-noise`` the next char is ``trans[cur]`` (a fixed random
    permutation of 1..vocab-1), else uniform — learnable structure for a
    next-char LSTM, never emitting the pad id 0 (so every target position
    is real and token- vs sequence-weighted metric aggregation coincide
    exactly across the two frameworks).  ``x`` is the stream's first L
    chars, ``y`` the next-char targets (the fed_shakespeare explicit-
    target blob shape).  Pass the same ``trans`` for train and val."""
    if trans is None:
        trans = rng.permutation(np.arange(1, vocab))
    out = {"users": [], "num_samples": [], "user_data": {},
           "user_data_label": {}}
    for u in range(users):
        xs, ys = [], []
        for _ in range(samples):
            stream = _markov_stream(rng, seq_len + 1, vocab, trans, noise)
            xs.append(stream[:seq_len])
            ys.append(stream[1:])
        name = f"{u:04d}"
        out["users"].append(name)
        out["num_samples"].append(samples)
        out["user_data"][name] = {"x": np.stack(xs)}
        out["user_data_label"][name] = np.stack(ys)
    return out


def gen_gru_blob(rng, users, seq_len, vocab=60, trans=None, noise=0.15):
    """nlg_gru-shaped blob: ONE word-id utterance per user (the
    reference's DynamicBatchSampler shuffles multi-utterance users with
    a wallclock-seeded epoch, so only 1 utt/user is order-deterministic;
    its frames budget == max_num_words then yields exactly one batch).
    Utterances are WORD STRINGS ("w<id>", all in-vocab) — the reference
    DatasetConfig has no ``preencoded`` field, so both frameworks
    tokenize through the same vocab file (case-backoff is a no-op for
    in-vocab words).  Ids stay in 1..vocab-1 (0 is the unk id the
    OOV-rejecting accuracy penalizes; never emitting it keeps both
    accuracy definitions trivially aligned), full length (no padding
    anywhere)."""
    if trans is None:
        trans = rng.permutation(np.arange(1, vocab))
    out = {"users": [], "num_samples": [], "user_data": {}}
    for u in range(users):
        stream = _markov_stream(rng, seq_len, vocab, trans, noise)
        name = f"{u:04d}"
        out["users"].append(name)
        out["num_samples"].append(1)
        out["user_data"][name] = {"x": [[f"w{i}" for i in stream]]}
    return out


def gen_bert_blob(rng, users, samples, seq_len, vocab, n_masked=3,
                  perm=None, n_special=5, mask_id=4):
    """MLM blob with PRECOMPUTED deterministic masking (VERDICT r3 item 4:
    "precomputed mask tensors fed as data to sidestep collator RNG").

    Token rule: even positions draw a random id in [n_special, vocab); each
    odd position is a fixed permutation of its left neighbor — masked
    tokens are recoverable from context, so MLM training has real signal.
    Masking: EXACTLY ``n_masked`` positions per sequence (a fixed count
    makes the reference's batch-size-weighted val loss coincide with the
    token-weighted mean our sum-form eval computes), HF 80/10/10 rule
    applied here with numpy RNG; ``x`` ships already masked, labels carry
    the original ids at masked slots and -100 elsewhere.  Pass the same
    ``perm`` for train and val."""
    content = vocab - n_special
    if perm is None:
        perm = rng.permutation(content)
    out = {"users": [], "num_samples": [], "user_data": {},
           "user_data_label": {}}
    for u in range(users):
        xs, ys = [], []
        for _ in range(samples):
            seq = np.empty(seq_len, np.int64)
            for t in range(seq_len):
                if t % 2 == 0:
                    seq[t] = n_special + rng.integers(content)
                else:
                    seq[t] = n_special + perm[seq[t - 1] - n_special]
            labels = np.full(seq_len, -100, np.int64)
            masked = seq.copy()
            pos = rng.choice(seq_len, size=n_masked, replace=False)
            for p in pos:
                labels[p] = seq[p]
                roll = rng.random()
                if roll < 0.8:
                    masked[p] = mask_id
                elif roll < 0.9:
                    masked[p] = n_special + rng.integers(content)
                # else: keep original (the 10% "unchanged" arm)
            xs.append(masked)
            ys.append(labels)
        name = f"{u:04d}"
        out["users"].append(name)
        out["num_samples"].append(samples)
        out["user_data"][name] = {"x": np.stack(xs)}
        out["user_data_label"][name] = np.stack(ys)
    return out


def make_bert_checkpoint(work, vocab, hidden=32, layers=2, heads=2,
                         intermediate=64, seed=0):
    """Build ONE local tiny-BERT checkpoint dir both frameworks load: the
    reference via ``model_name_or_path`` -> ``AutoModelForMaskedLM
    .from_pretrained`` (``experiments/mlm_bert/model.py:119-123`` — this
    exercises its pretrained path end to end), ours via the same config
    key -> ``FlaxBertForMaskedLM.from_pretrained(..., from_pt=True)``.
    Loading one torch-saved dir on both sides IS the identical-init
    transplant (HF owns the layout conversion).  Dropout is zeroed in the
    saved config so both forwards are deterministic.  The vocab.txt rows
    count must equal vocab_size: the reference resizes embeddings to
    ``len(tokenizer)`` (``model.py:137``), which must be a no-op."""
    import torch
    from transformers import BertConfig, BertForMaskedLM, BertTokenizer
    cfg = BertConfig(
        vocab_size=vocab, hidden_size=hidden, num_hidden_layers=layers,
        num_attention_heads=heads, intermediate_size=intermediate,
        max_position_embeddings=64,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    torch.manual_seed(seed)
    model = BertForMaskedLM(cfg)
    ckpt = os.path.join(work, "bert_ckpt")
    os.makedirs(ckpt, exist_ok=True)
    model.save_pretrained(ckpt)
    vocab_file = os.path.join(ckpt, "vocab.txt")
    with open(vocab_file, "w") as fh:
        for w in (["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
                  + [f"tok{i}" for i in range(vocab - 5)]):
            fh.write(w + "\n")
    BertTokenizer(vocab_file).save_pretrained(ckpt)
    return ckpt


def write_gru_blob(blob, path):
    with open(path, "w") as fh:
        json.dump(blob, fh)


def write_vocab(path, vocab):
    """Plain-txt vocab (one word per line): line index i maps word
    "w<i>" to id i in BOTH frameworks' loaders (nlg_gru utils
    ``load_vocab`` and ``msrflute_tpu.data.featurize.load_vocab``) —
    the vocab is load-bearing, since both sides tokenize the string
    blobs through it."""
    with open(path, "w") as fh:
        for i in range(vocab):
            fh.write(f"w{i}\n")


# ----------------------------------------------------------------------
# identical initial weights
# ----------------------------------------------------------------------
def lr_init(rng, input_dim=784, classes=10):
    scale = 1.0 / np.sqrt(input_dim)
    return {
        "w": rng.uniform(-scale, scale,
                         size=(classes, input_dim)).astype(np.float32),
        "b": rng.uniform(-scale, scale, size=(classes,)).astype(np.float32),
    }


def cnn_init(rng, classes=62):
    def kaiming(shape, fan_in):
        # torch kaiming_uniform_(a=sqrt(5)) default: bound = sqrt(6/((1+5)fan_in))
        bound = np.sqrt(6.0 / (6.0 * fan_in))
        return rng.uniform(-bound, bound, size=shape).astype(np.float32)

    def bias(shape, fan_in):
        bound = 1.0 / np.sqrt(fan_in)
        return rng.uniform(-bound, bound, size=shape).astype(np.float32)

    return {
        "conv1_w": kaiming((32, 1, 3, 3), 9), "conv1_b": bias((32,), 9),
        "conv2_w": kaiming((64, 32, 3, 3), 288), "conv2_b": bias((64,), 288),
        "fc1_w": kaiming((128, 9216), 9216), "fc1_b": bias((128,), 9216),
        "fc2_w": kaiming((classes, 128), 128), "fc2_b": bias((classes,), 128),
    }


def lstm_init(rng, vocab=90, embed=8, hidden=256):
    """torch-default init for the fed_shakespeare RNN: Embedding N(0,1)
    with the padding row zeroed, every nn.LSTM weight/bias
    uniform(-1/sqrt(H), 1/sqrt(H)), Linear kaiming-uniform(a=sqrt(5))
    (== uniform(-1/sqrt(fan_in), 1/sqrt(fan_in))) + matching bias."""
    k = 1.0 / np.sqrt(hidden)

    def u(shape):
        return rng.uniform(-k, k, size=shape).astype(np.float32)

    emb = rng.normal(size=(vocab, embed)).astype(np.float32)
    emb[0] = 0.0  # nn.Embedding(padding_idx=0) zeroes the pad row
    init = {"emb": emb}
    for layer, in_dim in ((0, embed), (1, hidden)):
        init[f"w_ih_l{layer}"] = u((4 * hidden, in_dim))
        init[f"w_hh_l{layer}"] = u((4 * hidden, hidden))
        init[f"b_ih_l{layer}"] = u((4 * hidden,))
        init[f"b_hh_l{layer}"] = u((4 * hidden,))
    bound = 1.0 / np.sqrt(hidden)
    init["fc_w"] = rng.uniform(-bound, bound,
                               size=(vocab, hidden)).astype(np.float32)
    init["fc_b"] = rng.uniform(-bound, bound,
                               size=(vocab,)).astype(np.float32)
    return init


def gru_init(rng, vocab=60, embed=16, hidden=64):
    """torch-default init for the nlg_gru GRU: embedding table
    uniform(±sqrt(3/E)) (Embedding.__init__), unembedding bias zeros,
    both GRU2 Linears kaiming-uniform(a=sqrt(5)) == uniform(±1/sqrt(in))
    with matching bias bounds, squeeze Linear (no bias) ditto."""
    def lin(out_dim, in_dim):
        b = 1.0 / np.sqrt(in_dim)
        return (rng.uniform(-b, b, size=(out_dim, in_dim)).astype(np.float32),
                rng.uniform(-b, b, size=(out_dim,)).astype(np.float32))

    delta = np.sqrt(3.0 / embed)
    table = rng.uniform(-delta, delta,
                        size=(vocab, embed)).astype(np.float32)
    w_ih, b_ih = lin(3 * hidden, embed)
    w_hh, b_hh = lin(3 * hidden, hidden)
    sq_w, _ = lin(embed, hidden)
    return {"table": table,
            "unembedding_bias": np.zeros((vocab,), np.float32),
            "w_ih": w_ih, "b_ih": b_ih, "w_hh": w_hh, "b_hh": b_hh,
            "squeeze": sq_w}


def save_torch_gru(init, path):
    import torch
    # the GRU model's submodules hang directly off self (no .net wrapper,
    # unlike the LR/CNN/RNN task classes)
    sd = {"embedding.table": torch.tensor(init["table"]),
          "embedding.unembedding_bias": torch.tensor(
              init["unembedding_bias"]),
          "rnn.w_ih.weight": torch.tensor(init["w_ih"]),
          "rnn.w_ih.bias": torch.tensor(init["b_ih"]),
          "rnn.w_hh.weight": torch.tensor(init["w_hh"]),
          "rnn.w_hh.bias": torch.tensor(init["b_hh"]),
          "squeeze.weight": torch.tensor(init["squeeze"])}
    torch.save(sd, path)


def save_flax_gru(init, path):
    """GRU2 keeps the three gates (r, i, n) stacked in one [3H, in]
    Linear on each side — our _ConvexGRUCell mirrors that layout exactly
    (same order, jnp.split), so only the Linear [out,in] -> flax [in,out]
    transposes apply."""
    from flax import serialization
    params = {
        "embedding": init["table"],
        "unembedding_bias": init["unembedding_bias"],
        "Scan_ConvexGRUCell_0": {
            "w_ih": {"kernel": init["w_ih"].T, "bias": init["b_ih"]},
            "w_hh": {"kernel": init["w_hh"].T, "bias": init["b_hh"]},
        },
        "squeeze": {"kernel": init["squeeze"].T},
    }
    with open(path, "wb") as fh:
        fh.write(serialization.msgpack_serialize(
            serialization.to_state_dict(params)))


def save_torch_lr(init, path):
    import torch
    sd = {"net.linear.weight": torch.tensor(init["w"]),
          "net.linear.bias": torch.tensor(init["b"])}
    torch.save(sd, path)


def save_torch_cnn(init, path):
    import torch
    sd = {
        "net.conv2d_1.weight": torch.tensor(init["conv1_w"]),
        "net.conv2d_1.bias": torch.tensor(init["conv1_b"]),
        "net.conv2d_2.weight": torch.tensor(init["conv2_w"]),
        "net.conv2d_2.bias": torch.tensor(init["conv2_b"]),
        "net.linear_1.weight": torch.tensor(init["fc1_w"]),
        "net.linear_1.bias": torch.tensor(init["fc1_b"]),
        "net.linear_2.weight": torch.tensor(init["fc2_w"]),
        "net.linear_2.bias": torch.tensor(init["fc2_b"]),
    }
    torch.save(sd, path)


def save_torch_lstm(init, path):
    import torch
    sd = {"net.embeddings.weight": torch.tensor(init["emb"]),
          "net.fc.weight": torch.tensor(init["fc_w"]),
          "net.fc.bias": torch.tensor(init["fc_b"])}
    for layer in (0, 1):
        for name in ("w_ih", "w_hh", "b_ih", "b_hh"):
            sd[f"net.lstm.{name.replace('w_', 'weight_').replace('b_', 'bias_')}_l{layer}"] = \
                torch.tensor(init[f"{name}_l{layer}"])
    torch.save(sd, path)


def save_flax_lstm(init, path, hidden=256):
    """torch nn.LSTM -> flax OptimizedLSTMCell: torch stacks the four
    gates (i, f, g, o) along dim 0 of weight_ih/weight_hh ([4H, in]) with
    two bias vectors (bias_ih + bias_hh, always summed in the cell); flax
    names per-gate Dense blocks — input kernels ``i{g}`` [in, H] without
    bias, hidden kernels ``h{g}`` [H, H] carrying the single bias."""
    from flax import serialization
    H = hidden
    params = {"Embed_0": {"embedding": init["emb"]},
              "Dense_0": {"kernel": init["fc_w"].T, "bias": init["fc_b"]}}
    for layer in (0, 1):
        cell = {}
        for k, g in enumerate("ifgo"):
            sl = slice(k * H, (k + 1) * H)
            cell[f"i{g}"] = {"kernel": init[f"w_ih_l{layer}"][sl].T}
            cell[f"h{g}"] = {"kernel": init[f"w_hh_l{layer}"][sl].T,
                             "bias": (init[f"b_ih_l{layer}"][sl]
                                      + init[f"b_hh_l{layer}"][sl])}
        params[f"OptimizedLSTMCell_{layer}"] = cell
    with open(path, "wb") as fh:
        fh.write(serialization.msgpack_serialize(
            serialization.to_state_dict(params)))


def save_flax_lr(init, path):
    from flax import serialization
    params = {"Dense_0": {"kernel": init["w"].T, "bias": init["b"]}}
    with open(path, "wb") as fh:
        fh.write(serialization.msgpack_serialize(
            serialization.to_state_dict(params)))


def save_flax_cnn(init, path):
    from flax import serialization
    # conv: [out,in,kh,kw] -> [kh,kw,in,out]
    # fc1 bridge: torch flattens NCHW [64,12,12] C-major; flax flattens
    # NHWC [12,12,64] HW-major -> permute fc1's input axis accordingly
    fc1 = init["fc1_w"].reshape(128, 64, 12, 12).transpose(0, 2, 3, 1)
    fc1 = fc1.reshape(128, 9216)
    params = {
        "Conv_0": {"kernel": init["conv1_w"].transpose(2, 3, 1, 0),
                   "bias": init["conv1_b"]},
        "Conv_1": {"kernel": init["conv2_w"].transpose(2, 3, 1, 0),
                   "bias": init["conv2_b"]},
        "Dense_0": {"kernel": fc1.T, "bias": init["fc1_b"]},
        "Dense_1": {"kernel": init["fc2_w"].T, "bias": init["fc2_b"]},
    }
    with open(path, "wb") as fh:
        fh.write(serialization.msgpack_serialize(
            serialization.to_state_dict(params)))


# ----------------------------------------------------------------------
# configs
# ----------------------------------------------------------------------
GRU_DIMS = {"vocab_size": 60, "embed_dim": 16, "hidden_dim": 64}


BERT_DIMS = {"vocab_size": 96, "hidden_size": 32, "num_hidden_layers": 2,
             "num_attention_heads": 2, "intermediate_size": 64}


def ref_config(task, rounds, users, batch, lr, init_path, outdim):
    model = {"model_type": {"lr": "LR", "cnn": "CNN", "lstm": "RNN",
                            "gru": "GRU", "bert": "BERT"}[task],
             "model_folder": f"experiments/parity_{task}/model.py"}
    if task == "bert":
        # init_path is the shared local checkpoint DIR (make_bert_checkpoint)
        # loaded through the reference's own pretrained path; no torch
        # state-dict transplant needed
        # schema (core/schema.py:24-31) REQUIRES model_name and
        # process_line_by_line; config validate (core/config.py:753-759)
        # propagates model_name (NOT model_name_or_path) into every data
        # config as the tokenizer path — so model_name must also be the
        # local checkpoint dir.  cache_dir/use_fast_tokenizer are read
        # unconditionally by the model/dataloaders.
        model["BERT"] = {
            "model": {"model_name": init_path,
                      "model_name_or_path": init_path,
                      "process_line_by_line": False,
                      # the model code's own default (True,
                      # model.py:69) crashes eval at preds.size();
                      # the experiment config class defaults False
                      # (experiments/mlm_bert/config.py:43)
                      "prediction_loss_only": False,
                      "cache_dir": None, "use_fast_tokenizer": False,
                      "mask_token_id": 4},
            "training": {"seed": 0, "label_smoothing_factor": 0,
                         "batch_size": batch},
        }
    else:
        model["pretrained_model_path"] = init_path
    if task == "lr":
        model.update({"input_dim": 784, "output_dim": outdim})
    elif task == "gru":
        model.update(GRU_DIMS)
    return {
        "model_config": model,
        "dp_config": {"enable_local_dp": False},
        "privacy_metrics_config": {"apply_metrics": False},
        "strategy": "FedAvg",
        "server_config": {
            "wantRL": False, "resume_from_checkpoint": False,
            "do_profiling": False,
            "optimizer_config": {"type": "sgd", "lr": 1.0},
            "annealing_config": {"type": "step_lr", "step_interval": "epoch",
                                 "gamma": 1.0, "step_size": 1000},
            "val_freq": 1, "rec_freq": 100000,
            "initial_val": True, "initial_rec": False,
            "max_iteration": rounds,
            "num_clients_per_iteration": users,
            "data_config": {
                "val": {"batch_size": 4096, "val_data": "val.json"},
                "test": {"batch_size": 4096, "test_data": "val.json"},
            },
            "type": "model_optimization",
            "aggregate_median": "softmax",
            "initial_lr_client": lr, "lr_decay_factor": 1.0,
            "weight_train_loss": "train_loss",
            "best_model_criterion": "loss",
            "fall_back_to_best_model": False, "softmax_beta": 1.0,
        },
        "client_config": {
            "do_profiling": False, "ignore_subtask": False,
            "data_config": {
                "train": {"batch_size": batch,
                          "list_of_train_data": "train.json",
                          "desired_max_samples": 100000},
            },
            "optimizer_config": {"type": "sgd", "lr": lr},
            "type": "optimization",
        },
    }


def tpu_config(task, rounds, users, batch, lr, init_path, outdim):
    model = {"model_type": {"lr": "LR", "cnn": "CNN", "lstm": "LSTM",
                            "gru": "GRU", "bert": "BERT"}[task]}
    if task == "bert":
        # same local checkpoint dir as the reference: identical init via
        # HF's own torch->flax conversion (models/bert.py from_pt fallback)
        model["BERT"] = {"model": {"model_name_or_path": init_path,
                                   "max_seq_length": outdim,
                                   "mask_token_id": 4,
                                   "premasked": True},
                         "training": {"seed": 0,
                                      "label_smoothing_factor": 0}}
    else:
        model["pretrained_model_path"] = init_path
    if task == "lr":
        model.update({"input_dim": 784, "num_classes": outdim,
                      "sigmoid_output": True})  # the reference LR quirk
    elif task == "lstm":
        # outdim carries seq_len for the lstm task (vocab is the
        # reference's hardcoded 90/8/256 architecture)
        model.update({"vocab_size": 90, "embed_dim": 8, "hidden_dim": 256,
                      "seq_len": outdim})
    elif task == "gru":
        model.update(dict(GRU_DIMS, max_num_words=outdim))
    else:
        model.update({"num_classes": outdim})
    return {
        "model_config": model,
        "strategy": "FedAvg",
        "server_config": {
            "optimizer_config": {"type": "sgd", "lr": 1.0},
            "annealing_config": {"type": "step_lr", "step_interval": "epoch",
                                 "gamma": 1.0, "step_size": 1000},
            "val_freq": 1, "rec_freq": 100000,
            "initial_val": True, "initial_rec": False,
            "max_iteration": rounds,
            "num_clients_per_iteration": users,
            "data_config": {
                "val": {"batch_size": 4096, "val_data": "val.json"},
                "test": {"batch_size": 4096, "test_data": "val.json"},
            },
            "type": "model_optimization",
            "initial_lr_client": lr, "lr_decay_factor": 1.0,
            "best_model_criterion": "loss",
        },
        "client_config": {
            "data_config": {
                "train": {"batch_size": batch,
                          "list_of_train_data": "train.json"},
            },
            "optimizer_config": {"type": "sgd", "lr": lr},
            "type": "optimization",
        },
    }


# ----------------------------------------------------------------------
# runners
# ----------------------------------------------------------------------
def build_ref_tree(scratch):
    """Symlink tree so the reference runs with our adapter experiments
    without writing to the read-only mount."""
    tree = os.path.join(scratch, "refrun")
    shutil.rmtree(tree, ignore_errors=True)
    os.makedirs(os.path.join(tree, "experiments"))
    for name in ("utils", "extensions", "e2e_trainer.py"):
        os.symlink(os.path.join(REFERENCE, name), os.path.join(tree, name))
    # core is symlinked per FILE so client.py can carry a one-line
    # runtime repair: the personalization branch unpacks TWO values from
    # train_desired_samples (core/client.py:427) which returns THREE
    # (core/trainer.py:339) — the reference's personalization training
    # crashes out of the box (docs/reference_quirks.md).  Patched in the
    # scratch tree only; nothing is copied into this repo.
    os.makedirs(os.path.join(tree, "core"))
    for name in os.listdir(os.path.join(REFERENCE, "core")):
        src = os.path.join(REFERENCE, "core", name)
        dst = os.path.join(tree, "core", name)
        if name == "client.py":
            with open(src) as fh:
                text = fh.read()
            broken = ("            train_loss, num_samples = "
                      "local_trainer.train_desired_samples(")
            fixed = ("            train_loss, num_samples, _ = "
                     "local_trainer.train_desired_samples(")
            assert broken in text, "reference client.py drifted; re-check"
            with open(dst, "w") as fh:
                fh.write(text.replace(broken, fixed, 1))
        else:
            os.symlink(src, dst)
    for name in os.listdir(os.path.join(REFERENCE, "experiments")):
        os.symlink(os.path.join(REFERENCE, "experiments", name),
                   os.path.join(tree, "experiments", name))
    for task in sorted(os.listdir(ADAPTERS)):  # every parity_* adapter
        if os.path.isdir(os.path.join(ADAPTERS, task)):
            os.symlink(os.path.join(ADAPTERS, task),
                       os.path.join(tree, "experiments", task))
    # the personalization server import is hardcoded to experiments/cv
    # (core/server.py:593-595) and the reference's own class there has a
    # stale constructor signature that crashes — remap cv to the
    # signature-current pass-through shim (see cv_server_shim/server.py)
    cv_link = os.path.join(tree, "experiments", "cv")
    os.remove(cv_link)
    os.symlink(os.path.join(ADAPTERS, "cv_server_shim"), cv_link)
    return tree


def run_reference(tree, cfg_path, data_dir, out_dir, task, metrics_out):
    """Run the reference in its REAL 2-process mode (server rank0 + worker
    rank1, gloo): the distributed path implements the documented FedAvg
    math.  (Thread mode, ``core/federated.py:683-707``, is avoided on
    purpose: on CPU ``tensor.to('cpu')`` is a no-copy alias, so its
    aggregate double-counts and the server steps from the last client's
    in-place-trained weights — measured in this harness, round-1 update
    ``0.1*g_last + 2*avg`` instead of ``avg``.  On GPU both artifacts
    disappear, so the published numbers are unaffected — but it is not the
    math to compare against.)"""
    env = dict(
        os.environ,
        REF_METRICS_OUT=metrics_out,
        PYTHONPATH=os.pathsep.join(
            [tree, os.path.join(REPO, "tools", "ref_shims")]),
        CUDA_VISIBLE_DEVICES="",
    )
    global _REF_RUN_SEQ
    proc = None
    for attempt in range(3):
        # fresh rendezvous port per invocation AND per attempt: a fixed
        # PID-derived port lands in TIME_WAIT between back-to-back
        # sequential torchruns of a multi-task run and the next rendezvous
        # fails flakily (observed: singles pass, sequences die on task 2+);
        # concurrent runs (pytest + manual) must not collide either
        _REF_RUN_SEQ += 1
        port = 20000 + (os.getpid() * 13 + _REF_RUN_SEQ * 101) % 20000
        cmd = [sys.executable, "-m", "torch.distributed.run",
               f"--nproc_per_node=2", f"--master-port={port}",
               os.path.join(REPO, "tools", "parity", "ref_launch.py"),
               "-dataPath", data_dir,
               "-outputPath", out_dir, "-config", cfg_path,
               "-task", task, "-backend", "gloo"]
        if os.path.exists(metrics_out):
            os.remove(metrics_out)  # a retry must not append to old metrics
        proc = subprocess.run(cmd, cwd=tree, env=env, capture_output=True,
                              text=True)
        if proc.returncode == 0:
            break
        sys.stderr.write(f"[parity] reference attempt {attempt + 1} failed "
                         f"rc={proc.returncode} (port {port}); tail:\n"
                         + proc.stdout[-2000:] + "\n" + proc.stderr[-3000:]
                         + "\n")
        # only rendezvous/bind flakiness justifies re-running a full
        # training; a deterministic crash (adapter bug, config typo)
        # would just burn two more identical multi-minute runs and bury
        # the real traceback.  NOTE "Connection closed by peer" is NOT
        # in this list: gloo prints it on rank0 for ANY rank1 crash.
        transient = ("Address already in use", "EADDRINUSE",
                     "failed to listen", "rendezvous")
        blob = proc.stdout + proc.stderr
        if not any(sig in blob for sig in transient):
            break
    if proc.returncode != 0:
        raise RuntimeError(f"reference trainer failed rc={proc.returncode}")
    return parse_ref_val_metrics(metrics_out)


def parse_ref_val_metrics(path):
    """Order-based alignment of a reference metrics.jsonl: Vals appear
    strictly in round order but the "Current iteration" marker flushes
    late (end-of-round metrics_payload), so align by ORDER — with
    initial_val on, the j-th val record is the state after j EVAL POINTS
    (round ``j * val_freq``; the parity harness runs val_freq=1 so j is
    the round directly, ``longrun.py`` rescales).  Shared by
    :func:`run_reference` and the longrun's reuse-from-disk path — ONE
    copy of the alignment logic."""
    rounds = {}
    j = {"Val loss": 0, "Val acc": 0}
    with open(path) as fh:
        for line in fh:
            rec = json.loads(line)
            name = rec.get("name")
            if name in j:
                rounds.setdefault(j[name], {})[name] = float(rec["value"])
                j[name] += 1
    return rounds


def run_msrflute(cfg_path, data_dir, out_dir, task, name_map=None,
                 env_override=None, timeout=None):
    """``name_map`` maps OUR metric names onto the canonical comparison
    keys ("Val loss"/"Val acc") — the personalization mode compares the
    reference's personalized Val metrics against our "Personalized val
    loss/acc" records.  ``env_override`` replaces env vars for this run:
    conv-heavy programs must drop to 2 virtual devices with
    single-threaded Eigen on this 1-core host, or XLA's in-process
    AllReduce rendezvous (hard 40 s termination, ``rendezvous.cc:127``)
    SIGABRTs when a starved device thread misses the collective.
    ``timeout`` (secs) kills the TRAINER ITSELF on expiry — a queue job
    must not wrap this call in a shell ``timeout``, which would kill
    only the orchestrator and orphan the trainer holding the
    single-client tunnel claim (docs/RUNBOOK.md failure mode 4)."""
    env = dict(
        os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    env.update(env_override or {})
    cmd = [sys.executable, os.path.join(REPO, "e2e_trainer.py"),
           "-config", cfg_path, "-dataPath", data_dir,
           "-outputPath", out_dir, "-task", task]
    proc = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=timeout)
    if proc.returncode != 0:
        sys.stderr.write(proc.stdout[-4000:] + "\n" + proc.stderr[-6000:])
        raise RuntimeError(f"msrflute_tpu trainer failed rc={proc.returncode}")
    name_map = name_map or {"Val loss": "Val loss", "Val acc": "Val acc"}
    rounds = {}
    with open(os.path.join(out_dir, "log", "metrics.jsonl")) as fh:
        for line in fh:
            rec = json.loads(line)
            if rec.get("name") in name_map:
                rounds.setdefault(int(rec["step"]), {})[
                    name_map[rec["name"]]] = float(rec["value"])
    return rounds


# ----------------------------------------------------------------------
# orchestration
# ----------------------------------------------------------------------
TASKS = {
    # task: (shape, model classes, users, samples/user, batch, client_lr,
    #        data classes)
    # CNN: the reference model hardcodes 62 outputs (CNN_DropOut(False)),
    # but the synthetic blob only uses the first 10 labels with wide
    # separation — learnable at a dropout-gentle lr, so both trajectories
    # visibly descend instead of hovering at chance or diverging.
    "lr": ((784,), 10, 16, 32, 64, 0.1, 10),
    "cnn": ((28, 28), 62, 8, 48, 64, 0.05, 10),
    # LSTM: shape slot carries seq_len; "classes" is the model's hardcoded
    # vocab (90).  No dropout -> the trajectory is fully deterministic and
    # compared strictly, like LR (modulo deeper f32 recurrence noise).
    # lr=4.0: the protocol is exact full-batch SGD (1 batch/client, full
    # participation), which is stable at large lr and needs it — the
    # next-char rule only becomes learnable within ~100 rounds there
    # (probed offline; see ROUNDS_OVERRIDE).
    "lstm": ((24,), 90, 8, 16, 16, 4.0, None),
    # GRU (nlg_gru): shape = seq_len (== max_num_words), classes = vocab
    # (dims in GRU_DIMS); ONE utterance per user — the reference's
    # DynamicBatchSampler seeds its shuffle from wallclock randomness,
    # so only single-batch users are order-deterministic (its frames
    # budget == max_num_words then yields exactly one batch).  lr=1.0 is
    # stable full-batch (4.0 diverges — probed offline).
    # 48 users x 11 transitions must cover the 59-way next-word rule, or
    # val loss bottoms out early and rises (measured at 16 users: exact
    # tracking but the "loss halved" learning criterion fails on
    # overfitting, not on mismatch)
    "gru": ((12,), 60, 48, 1, 4, 1.0, None),
    # BERT (mlm_bert): shape = seq_len, classes = vocab (arch in
    # BERT_DIMS); pre-masked blobs (gen_bert_blob) + one shared local
    # checkpoint dir; lr probed offline (full-batch SGD on the pooled
    # data — see docstring protocol note)
    "bert": ((16,), 96, 8, 16, 16, 0.5, None),
}

# per-task default round counts, used when the caller leaves --rounds
# unset: single-local-step protocols can need more rounds to show
# learning (the reference runs exactly one epoch per round and
# multi-batch rounds would be shuffle-order-incomparable).  An explicit
# --rounds always wins (smoke tests pass --rounds 3).
DEFAULT_ROUNDS = 20
ROUNDS_BY_TASK = {"lstm": 100, "gru": 100, "bert": 30}


# ----------------------------------------------------------------------
# extension modes (VERDICT r3 item 2): the same deterministic LR
# protocol with the reference's extensions switched ON — DGA softmax
# weighting, gradient quantization, and the local/global DP dance.
# ----------------------------------------------------------------------
def _dga_strategy(rc, tc):
    """Run DGA both sides (reference ``core/strategies/dga.py``; ours
    ``strategies/dga.py``): softmax client weight
    ``exp(-beta * train_loss / num_samples)`` with beta = softmax_beta.
    The base ref_config already carries aggregate_median/softmax_beta/
    weight_train_loss; FedAvg ignores them, DGA consumes them."""
    rc["strategy"] = "DGA"
    tc["strategy"] = "DGA"
    tc["server_config"]["aggregate_median"] = "softmax"
    tc["server_config"]["softmax_beta"] = 1.0
    tc["server_config"]["weight_train_loss"] = "train_loss"


def _quant(rc, tc, thresh=0.5, bits=8):
    """Gradient quantization (reference ``extensions/quantization/
    quant.py:9-50``, invoked from DGA's client payload, ``dga.py:148-149``):
    per-layer min/max binning into 2**bits levels, components with
    |g| <= quantile(|g|, thresh) zeroed.  The reference quantizes AFTER the
    weight multiply, we BEFORE — binning is scale-equivariant for w > 0
    (labels, bucket indices and the threshold all scale by w), so the two
    orders agree to f32 rounding."""
    for c in (rc, tc):
        c["client_config"]["quant_thresh"] = thresh
        c["client_config"]["quant_bits"] = bits


def _dp(rc, tc, *, eps, max_grad, max_weight=1.0, global_sigma=None):
    """Local (+optionally global) DP (reference ``extensions/privacy/
    __init__.py:154-201``): eps < 0 is CLIP-ONLY — fully deterministic;
    eps > 0 renormalizes the update to exactly max_grad norm, then adds
    Gaussian noise with sigma = sqrt(2 ln(1.25/delta)) * sensitivity/eps
    to [update, scaled weight] jointly, clamps the noised weight to
    [min_weight, max_weight] and unscales.  Huge eps -> vanishing sigma:
    the FULL eps>0 dance runs near-deterministically, so any semantic
    divergence (a wrong clamp, scale, or sensitivity) shows as trajectory
    drift while honest f32 noise stays tiny.  global_sigma=0.0 exercises
    the global-DP unroll/noise/update path exactly (noise*0)."""
    dp = {
        "enable_local_dp": True, "eps": eps, "delta": 1e-7,
        "max_grad": max_grad, "max_weight": max_weight,
        "min_weight": 1e-7, "weight_scaler": 1.0,
    }
    if global_sigma is not None:
        # must be > 0: the reference accountant computes (1/sigma)^2 and
        # crashes on exactly 0 (ZeroDivisionError at privacy/__init__.py:227;
        # its OverflowError for small sigma IS caught and logged as mu=-1)
        dp["enable_global_dp"] = True
        dp["global_sigma"] = global_sigma
    rc["dp_config"] = dict(dp)
    tc["dp_config"] = dict(dp)


def _personalization(rc, tc):
    """Personalization server both sides (reference ``core/client.py:
    387-443`` train path + ``:190-220`` eval path; ours
    ``engine/personalization.py``).  Alignment choices, each mirrored on
    both sides: local models cold-start from the SEED FILE (the
    reference's bare ``make_model`` random init is unreproducible — the
    parity_pers adapter loads pretrained_model_path, ours sets
    ``personalization_init: initial``); eval interpolates LOG-probs
    (``personalization_interp: logprobs``, the cv model contract); val
    data = the train blob so every val user owns a local model (the
    reference looks up ``<user>_model.tar`` by val-user NAME)."""
    rc["server_config"]["type"] = "personalization"
    rc["model_config"]["model_folder"] = "experiments/parity_pers/model.py"
    rc["client_config"]["convex_model_interp"] = 0.75
    rc["server_config"]["data_config"]["val"]["val_data"] = "train.json"
    rc["server_config"]["data_config"]["test"]["test_data"] = "train.json"
    tc["server_config"]["type"] = "personalization"
    tc["server_config"]["personalization_init"] = "initial"
    tc["server_config"]["personalization_interp"] = "logprobs"
    tc["client_config"]["convex_model_interp"] = 0.75
    tc["server_config"]["data_config"]["val"]["val_data"] = "train.json"
    tc["server_config"]["data_config"]["test"]["test_data"] = "train.json"


def _cnn_nodropout(rc, tc):
    """Dropout zeroed on both sides (reference: the ``parity_cnn_nd``
    adapter subclasses its CNN and sets both Dropout p=0; ours: the
    ``dropout1/dropout2`` model-config knobs).  The only RNG in the CNN
    family disappears, so the comparison is held to trajectory-exact."""
    rc["model_config"]["model_folder"] = "experiments/parity_cnn_nd/model.py"
    tc["model_config"]["dropout1"] = 0.0
    tc["model_config"]["dropout2"] = 0.0


MODES = {
    # deterministic: the CNN family with its one RNG source (dropout)
    # removed — upgrades the cnn entry from endpoint-grade to
    # trajectory-exact (VERDICT r3 item 3)
    "cnn_nodropout": {"base": "cnn", "mutate": [_cnn_nodropout],
                      "criteria": "exact",
                      "tpu_env": {"XLA_FLAGS":
                                  "--xla_force_host_platform_device_count=2 "
                                  "--xla_cpu_multi_thread_eigen=false"}},
    # deterministic: per-user local models + convex-alpha interpolation
    # (compares the reference's personalized Val metrics against our
    # "Personalized val loss/acc" records)
    "pers": {"mutate": [_personalization], "criteria": "near",
             "tpu_metrics": {"Personalized val loss": "Val loss",
                             "Personalized val acc": "Val acc"}},
    # deterministic: UNEVEN user sizes under plain FedAvg — the
    # sample-count weights (reference fedavg.py:80: weight =
    # trainer.num_samples) stop cancelling in the normalized aggregate,
    # so proportional weighting itself is under test; every other family
    # ships equal-sized users
    "lr_uneven": {"mutate": [], "criteria": "exact", "uneven_users": True},
    # deterministic: non-trivial SERVER optimizers — every other family
    # runs the canonical SGD(lr=1.0) server step, so the ModelUpdater
    # semantics (our optax step vs the reference's torch.optim step on
    # the aggregated pseudo-gradient, core/trainer.py update_model) are
    # otherwise only exercised in their degenerate form.  torch Adam's
    # m_hat/(sqrt(v_hat)+eps) == optax.adam(eps_root=0); torch SGD
    # momentum buf = mu*buf + g == optax trace (nesterov off).
    "lr_server_adam": {
        "mutate": [lambda rc, tc: [
            c["server_config"].update(
                {"optimizer_config": {"type": "adam", "lr": 0.02}})
            for c in (rc, tc)]],
        "criteria": "exact"},
    "lr_server_momentum": {
        "mutate": [lambda rc, tc: [
            c["server_config"].update(
                {"optimizer_config": {"type": "sgd", "lr": 1.0,
                                      "momentum": 0.9}})
            for c in (rc, tc)]],
        "criteria": "exact"},
    # deterministic: CLIENT-side Adam — the per-client optimizer state
    # machinery (fresh optax.adam per round under vmap vs the
    # reference's fresh torch.optim.Adam per process_round) on real
    # bias-corrected first steps
    "lr_client_adam": {
        "mutate": [lambda rc, tc: [
            c["client_config"].update(
                {"optimizer_config": {"type": "adam", "lr": 0.05}})
            for c in (rc, tc)]],
        "criteria": "exact"},
    # deterministic: layer freezing — the aggregate skips the frozen
    # layer's pseudo-gradient (reference zeroes p.grad by exact
    # named_parameters match, fedavg.py:83-88 reading
    # model_config.freeze_layer; ours zeroes by flax path fragment from
    # client_config.freeze_layer) — each side names the SAME layer in
    # its own parameter vocabulary
    "lr_freeze": {
        "mutate": [lambda rc, tc: (
            rc["model_config"].update({"freeze_layer": "net.linear.weight"}),
            tc["client_config"].update({"freeze_layer": "Dense_0/kernel"}))],
        "criteria": "exact"},
    # deterministic: desired_max_samples BELOW the per-user sample count
    # with one batch per client — the reference's batch-granular cap
    # (loop-top check, core/trainer.py:363-364) means the full batch
    # still trains; an exact-sample cap would train on fewer samples
    # and shift both the pseudo-gradient and the num_samples weight
    "lr_maxsamples": {
        "mutate": [lambda rc, tc: [
            c["client_config"]["data_config"]["train"].update(
                {"desired_max_samples": 25}) for c in (rc, tc)]],
        "criteria": "exact"},
    # deterministic: best-model fallback + server momentum — the
    # reference reloads best_val_<criterion> EVERY val round
    # (server.py:475,561-571, unconditional), a no-op on improvement
    # (evaluation.run just overwrote best with current) and a rollback
    # otherwise; ours folds that into fall-back-iff-worse.  On this
    # protocol val improves monotonically (probed at lr 1/12 and with
    # momentum 0.95: the sigmoid LR never overshoots), so what this
    # family pins is the no-op-reload equivalence with live server
    # momentum state riding along — the rollback-on-worsening sub-path
    # remains covered by unit tests only.
    "lr_fallback": {
        "mutate": [lambda rc, tc: [
            (c["server_config"].update({"fall_back_to_best_model": True,
                                        "best_model_criterion": "loss",
                                        "initial_lr_client": 1.0,
                                        "optimizer_config": {
                                            "type": "sgd", "lr": 1.0,
                                            "momentum": 0.95}}),
             c["client_config"]["optimizer_config"].update({"lr": 1.0}))
            for c in (rc, tc)]],
        "criteria": "exact"},
    # deterministic: the LSTM family at a STABLE lr — the committed lstm
    # entry needs lr=4.0 for the rule to become learnable, which is
    # exactly where f32 chaos amplifies mid-trajectory (early-exact +
    # endpoint criteria); at lr=0.5 the dynamics contract and the deep
    # recurrence is held to pointwise agreement over the whole run
    "lstm_stable_lr": {
        "base": "lstm",
        "mutate": [lambda rc, tc: [
            (c["server_config"].update({"initial_lr_client": 0.5}),
             c["client_config"]["optimizer_config"].update({"lr": 0.5}))
            for c in (rc, tc)]],
        "criteria": "near"},
    # deterministic: DGA softmax weighting only
    "dga": {"mutate": [_dga_strategy], "criteria": "exact"},
    # DGA softmax weighting on the GRU base: exercises the
    # train_loss/num_samples metric where the COUNTING UNIT matters —
    # nlg_gru batches carry total_frames, so the reference counts WORDS
    # (core/trainer.py:402-403) while rows would be utterances; a
    # counting mismatch shifts every client's softmax weight even with
    # equal-sized users (unlike FedAvg, where a constant factor cancels
    # in the normalized aggregate)
    "gru_dga": {"base": "gru", "mutate": [_dga_strategy],
                "criteria": "near"},
    # deterministic: DGA + per-layer 8-bit quantization at the 0.5 quantile
    "dga_quant": {"mutate": [_dga_strategy, _quant], "criteria": "near"},
    # deterministic: the same transforms over CONV pseudo-gradients —
    # 4-D kernel tensors exercise per-layer min/max binning and the
    # |g|-quantile threshold on shapes the LR base never produces
    # (dropout zeroed so the conv family stays deterministic)
    "cnn_dga_quant": {"base": "cnn",
                      "mutate": [_cnn_nodropout, _dga_strategy, _quant],
                      "criteria": "near",
                      "tpu_env": {"XLA_FLAGS":
                                  "--xla_force_host_platform_device_count=2 "
                                  "--xla_cpu_multi_thread_eigen=false"}},
    # deterministic: clip-only local DP (eps < 0) under DGA
    "dp_clip": {"mutate": [_dga_strategy,
                           lambda rc, tc: _dp(rc, tc, eps=-1.0,
                                              max_grad=0.05)],
                "criteria": "near"},
    # near-deterministic: the full eps>0 dance at vanishing sigma, plus
    # the global-DP path at near-zero sigma (exactly 0 crashes the
    # reference accountant; 1e-4 keeps the added noise ~1e-4 relative).
    # max_grad must be SMALL: the eps>0 path renormalizes every update to
    # exactly max_grad norm, so a large value forces constant big steps
    # that blow the sigmoid-output LR up to inf loss -> every weight
    # filtered to 0 -> the reference divides by zero clients (measured at
    # max_grad=0.5, round ~8)
    "dp_tiny_noise": {"mutate": [_dga_strategy,
                                 lambda rc, tc: _dp(rc, tc, eps=1e8,
                                                    max_grad=0.05,
                                                    global_sigma=1e-4)],
                      "criteria": "near"},
    # statistical: real noise, RNG incomparable across torch/jax — the
    # criterion is an envelope (both learn; endpoints in a band)
    "dp_envelope": {"mutate": [_dga_strategy,
                               lambda rc, tc: _dp(rc, tc, eps=1000.0,
                                                  max_grad=0.05,
                                                  global_sigma=0.1)],
                    "criteria": "envelope"},
}


def _judge_mode(traj, criteria):
    """ok/verdict for an extension mode run on the deterministic LR base."""
    diffs_loss = [r["Val loss"]["abs_diff"] for r in traj
                  if r["Val loss"]["abs_diff"] is not None]
    diffs_acc = [r["Val acc"]["abs_diff"] for r in traj
                 if r["Val acc"]["abs_diff"] is not None]
    max_dl = max(diffs_loss) if diffs_loss else None
    max_da = max(diffs_acc) if diffs_acc else None
    ok, verdict = False, "insufficient data"
    if max_dl is None or max_da is None or not traj:
        return ok, verdict, max_dl, max_da
    if criteria == "exact":
        ok = max_dl < 1e-4 and max_da == 0.0
        verdict = ("trajectory-exact (f32 accumulation noise only)" if ok
                   else "MISMATCH beyond float noise")
    elif criteria == "near":
        # deterministic payload transforms, but with hard nonlinearities
        # (quant bin edges, clip thresholds) that can amplify one-ulp
        # disagreements into a visible-but-bounded wobble
        ok = max_dl < 5e-3 and max_da <= 0.02
        verdict = ("trajectory matched within transform-boundary noise"
                   if ok else "MISMATCH beyond transform-boundary noise")
    else:  # envelope
        ref0 = traj[0]["Val loss"]["reference"]
        fin = traj[-1]
        rl = fin["Val loss"]["reference"]
        tl = fin["Val loss"]["msrflute_tpu"]
        ra = fin["Val acc"]["reference"]
        ta = fin["Val acc"]["msrflute_tpu"]
        if None not in (ref0, rl, tl, ra, ta):
            learned = rl < 0.8 * ref0 and tl < 0.8 * ref0
            ok = (learned
                  and (abs(rl - tl) < 0.15
                       or abs(rl - tl) / max(rl, tl) < 0.15)
                  and abs(ra - ta) < 0.1)
        verdict = ("both learn under matched DP noise scale; endpoints "
                   "in statistical envelope" if ok
                   else "MISMATCH beyond DP statistical envelope")
    return ok, verdict, max_dl, max_da


def run_task(task, rounds, scratch, mode=None):
    shape, classes, users, samples, batch, lr, data_classes = TASKS[task]
    if rounds is None:
        rounds = ROUNDS_BY_TASK.get(task, DEFAULT_ROUNDS)
    rng = np.random.default_rng(7)
    work = os.path.join(scratch, mode or task)
    shutil.rmtree(work, ignore_errors=True)
    data_ref = os.path.join(work, "data_ref")
    data_tpu = os.path.join(work, "data_tpu")
    os.makedirs(data_ref)
    os.makedirs(data_tpu)

    if task == "lstm":
        seq_len = shape[0]
        trans = rng.permutation(np.arange(1, classes))
        train = gen_lstm_blob(rng, users, samples, seq_len, vocab=classes,
                              trans=trans)
        val = gen_lstm_blob(rng, 4, 32, seq_len, vocab=classes, trans=trans)
        # int sequences need no layout conversion between the frameworks
        for blob, name in ((train, "train.json"), (val, "val.json")):
            write_blob(blob, os.path.join(data_ref, name))
            write_blob(blob, os.path.join(data_tpu, name))
        init = lstm_init(rng, vocab=classes)
        save_torch_lstm(init, os.path.join(work, "init.pt"))
        save_flax_lstm(init, os.path.join(work, "init.msgpack"))
    elif task == "bert":
        seq_len = shape[0]
        perm = rng.permutation(classes - 5)
        train = gen_bert_blob(rng, users, samples, seq_len, vocab=classes,
                              perm=perm)
        val = gen_bert_blob(rng, 4, 32, seq_len, vocab=classes, perm=perm)
        for blob, name in ((train, "train.json"), (val, "val.json")):
            write_blob(blob, os.path.join(data_ref, name))
            write_blob(blob, os.path.join(data_tpu, name))
        # one torch-saved checkpoint dir IS the identical init (both
        # sides' pretrained loaders point at it)
        bert_ckpt = make_bert_checkpoint(work, vocab=classes,
                                         hidden=BERT_DIMS["hidden_size"],
                                         layers=BERT_DIMS["num_hidden_layers"],
                                         heads=BERT_DIMS["num_attention_heads"],
                                         intermediate=BERT_DIMS["intermediate_size"])
    elif task == "gru":
        seq_len = shape[0]
        trans = rng.permutation(np.arange(1, classes))
        train = gen_gru_blob(rng, users, seq_len, vocab=classes,
                             trans=trans)
        val = gen_gru_blob(rng, 16, seq_len, vocab=classes, trans=trans)
        for blob, name in ((train, "train.json"), (val, "val.json")):
            write_gru_blob(blob, os.path.join(data_ref, name))
            write_gru_blob(blob, os.path.join(data_tpu, name))
        write_vocab(os.path.join(work, "vocab.txt"), classes)
        init = gru_init(rng, vocab=classes, embed=GRU_DIMS["embed_dim"],
                        hidden=GRU_DIMS["hidden_dim"])
        save_torch_gru(init, os.path.join(work, "init.pt"))
        save_flax_gru(init, os.path.join(work, "init.msgpack"))
    else:
        means = rng.normal(size=(data_classes,) + shape).astype(np.float32)
        if mode is not None and MODES[mode].get("uneven_users"):
            # spread 8..(8+3(users-1)) — stays under the one-batch cap
            # (batch_size 64) so rounds remain shuffle-order-comparable
            samples = [8 + 3 * u for u in range(users)]
        train = gen_blob(rng, users, samples, shape, data_classes, sep=3.0,
                         means=means)
        val = gen_blob(rng, 4, 64, shape, data_classes, sep=3.0, means=means)
        # the reference __getitem__ transposes images; pre-swap its copy so
        # both frameworks train on identical tensors
        for blob, name in ((train, "train.json"), (val, "val.json")):
            write_blob(blob, os.path.join(data_ref, name),
                       transpose_images=True)
            write_blob(blob, os.path.join(data_tpu, name),
                       transpose_images=False)

        if task == "lr":
            init = lr_init(rng, 784, classes)
            save_torch_lr(init, os.path.join(work, "init.pt"))
            save_flax_lr(init, os.path.join(work, "init.msgpack"))
        else:
            init = cnn_init(rng, classes)
            save_torch_cnn(init, os.path.join(work, "init.pt"))
            save_flax_cnn(init, os.path.join(work, "init.msgpack"))

    import yaml
    tree = build_ref_tree(scratch)
    outdim = shape[0] if task in ("lstm", "gru") else classes  # seq_len
    if task == "bert":
        # one shared checkpoint DIR is the init for both sides
        ref_init = tpu_init = bert_ckpt
    else:
        ref_init = os.path.join(work, "init.pt")
        tpu_init = os.path.join(work, "init.msgpack")
    rc = ref_config(task, rounds, users, batch, lr, ref_init, outdim)
    tc = tpu_config(task, rounds, users, batch, lr, tpu_init, outdim)
    if mode is not None:
        for mutate in MODES[mode]["mutate"]:
            mutate(rc, tc)
    if task == "gru":
        # the nlg_gru loaders read their knobs from the per-split data
        # blocks: plain-txt vocab (absolute path), frames budget ==
        # max_num_words (-> one utterance per batch), preencoded int rows
        gru_keys = {"vocab_dict": os.path.join(work, "vocab.txt"),
                    "max_num_words": shape[0], "pin_memory": False,
                    "unsorted_batch": True}
        rc["server_config"]["data_config"]["val"].update(gru_keys)
        rc["server_config"]["data_config"]["test"].update(gru_keys)
        rc["client_config"]["data_config"]["train"].update(gru_keys)
        # our side tokenizes through the SAME vocab file
        tc["model_config"]["vocab_dict"] = os.path.join(work, "vocab.txt")
    ref_cfg = os.path.join(work, "ref.yaml")
    tpu_cfg = os.path.join(work, "tpu.yaml")
    with open(ref_cfg, "w") as fh:
        yaml.safe_dump(rc, fh)
    with open(tpu_cfg, "w") as fh:
        yaml.safe_dump(tc, fh)

    print(f"[parity:{task}] running reference (torch, 2-process gloo)...")
    ref = run_reference(tree, ref_cfg, data_ref,
                        os.path.join(work, "out_ref"), f"parity_{task}",
                        os.path.join(work, "ref_metrics.jsonl"))
    print(f"[parity:{task}] running msrflute_tpu (8-dev virtual cpu mesh)...")
    tpu_name_map, tpu_env = None, None
    if mode is not None:
        tpu_name_map = MODES[mode].get("tpu_metrics")
        tpu_env = MODES[mode].get("tpu_env")
    tpu = run_msrflute(tpu_cfg, data_tpu, os.path.join(work, "out_tpu"),
                       f"parity_{task}", name_map=tpu_name_map,
                       env_override=tpu_env)

    common = sorted(set(ref) & set(tpu))
    traj = []
    for r in common:
        row = {"round": r}
        for key in ("Val loss", "Val acc"):
            rv, tv = ref[r].get(key), tpu[r].get(key)
            row[key] = {"reference": rv, "msrflute_tpu": tv,
                        "abs_diff": (abs(rv - tv)
                                     if rv is not None and tv is not None
                                     else None)}
        traj.append(row)
    diffs_loss = [row["Val loss"]["abs_diff"] for row in traj
                  if row["Val loss"]["abs_diff"] is not None]
    diffs_acc = [row["Val acc"]["abs_diff"] for row in traj
                 if row["Val acc"]["abs_diff"] is not None]
    max_dl = max(diffs_loss) if diffs_loss else None
    max_da = max(diffs_acc) if diffs_acc else None
    if mode is not None:
        ok, verdict, _, _ = _judge_mode(traj, MODES[mode]["criteria"])
    elif task == "bert":
        # fully deterministic protocol (pre-masked data, zero dropout in
        # the saved config, sequential order): held to trajectory
        # exactness within an f32 band.  The VERDICT r3 scope for this
        # family is a short deterministic trajectory + transplant
        # forward-exactness — NOT a learning demonstration: the 2-layer
        # 32-wide model cannot learn the 91-way permutation rule in tens
        # of full-batch steps (probed offline with torch SGD and Adam at
        # 5 lrs; val stays at the ln(91) chance floor while train loss
        # moves), so the criterion instead demands material MOVEMENT
        # (the dynamics are exercised) plus pointwise agreement.
        ref0 = traj[0]["Val loss"]["reference"] if traj else None
        rl = traj[-1]["Val loss"]["reference"] if traj else None
        moved = (ref0 is not None and rl is not None
                 and abs(rl - ref0) > 5e-3)
        ok = (max_dl is not None and max_dl < 5e-3
              and max_da is not None and max_da <= 0.02 and moved)
        verdict = ("trajectory matched within f32 band; dynamics "
                   "exercised (loss moves materially)" if ok
                   else "MISMATCH beyond f32 band (or no movement)")
    elif task == "lr":
        # fully deterministic protocol: must be trajectory-exact
        ok = max_dl is not None and max_dl < 1e-4 and max_da == 0.0
        verdict = ("trajectory-exact (float32 accumulation noise only)"
                   if ok else "MISMATCH beyond float noise")
    elif task in ("lstm", "gru"):
        # no dropout -> fully deterministic, but chaotically SENSITIVE:
        # measured on this protocol (committed PARITY.json), the sides
        # agree to < 1e-3 for the first ~30 rounds (pure f32
        # accumulation-order noise), then the steep-descent phase
        # amplifies that noise exponentially — pointwise gaps transiently
        # reach O(1) mid-descent (1.45 at round 67 in the committed run,
        # where the two sides cross the cliff a few rounds apart) — and
        # the gap CONTRACTS again as both converge (0.08 by round 100).
        # That grow-then-recontract shape is the signature of trajectory
        # sensitivity, not of a semantic difference (a wrong lr or
        # denominator would drift proportionally from round 1).  Honest
        # criteria, mirroring the CNN rationale: the early phase is
        # strictly exact, both sides learn the next-char rule, and the
        # endpoints match.
        early = [row["Val loss"]["abs_diff"] for row in traj[:26]
                 if row["Val loss"]["abs_diff"] is not None]
        ref0 = traj[0]["Val loss"]["reference"] if traj else None
        a0r = traj[0]["Val acc"]["reference"] if traj else None
        a0t = traj[0]["Val acc"]["msrflute_tpu"] if traj else None
        fin = traj[-1] if traj else None
        rl = (fin or {}).get("Val loss", {}).get("reference")
        tl = (fin or {}).get("Val loss", {}).get("msrflute_tpu")
        ra = (fin or {}).get("Val acc", {}).get("reference")
        ta = (fin or {}).get("Val acc", {}).get("msrflute_tpu")
        ok = False
        if early and None not in (ref0, a0r, a0t, rl, tl, ra, ta):
            # "both learned" must respect the task's entropy floor: the
            # noisy next-token rules have irreducible CE (noise entropy +
            # the unpredictable first token), so demand a clear loss drop
            # AND a decisive accuracy gain rather than an arbitrary
            # loss-halving (measured: gru converges to ~2.3 from 4.1 at
            # 72% accuracy — halving is unreachable there by design)
            learned = (rl < 0.8 * ref0 and tl < 0.8 * ref0
                       and ra - a0r > 0.25 and ta - a0t > 0.25)
            ok = (max(early) < 5e-3
                  and learned
                  # absolute-or-relative: near-zero converged losses make
                  # a pure relative test divide by ~0 (CNN branch ditto)
                  and (abs(rl - tl) < 0.05
                       or abs(rl - tl) / max(rl, tl) < 0.1)
                  and abs(ra - ta) < 0.05)
        verdict = ("early-trajectory exact (f32 noise only); both learn "
                   "the rule; endpoints matched within chaotic-"
                   "sensitivity noise" if ok
                   else "MISMATCH beyond deterministic-sensitivity criteria")
    else:
        # CNN has torch/jax-incomparable dropout RNG, and during the steep
        # descent phase a small RNG-induced time offset yields large
        # pointwise loss gaps — so a max-abs-diff band is the wrong
        # metric.  The honest criteria: round 0 (dropout inactive) exact,
        # both trajectories actually LEARN (final loss well below round 0),
        # and the endpoints agree (relative loss diff + acc diff small).
        r0 = traj[0]["Val loss"]["abs_diff"] if traj else None
        fin = traj[-1] if traj else None
        ref0 = traj[0]["Val loss"]["reference"] if traj else None
        ok = False
        vals = ((fin or {}).get("Val loss", {}), (fin or {}).get("Val acc", {}))
        rl, tl = vals[0].get("reference"), vals[0].get("msrflute_tpu")
        ra, ta = vals[1].get("reference"), vals[1].get("msrflute_tpu")
        if None not in (r0, ref0, rl, tl, ra, ta):
            # endpoints agree: absolute OR relative — near-converged losses
            # (both ~1e-3) make a pure relative test meaningless
            close = (abs(rl - tl) < 0.05
                     or abs(rl - tl) / max(rl, tl) < 0.05)
            ok = (r0 < 1e-4
                  and rl < 0.8 * ref0 and tl < 0.8 * ref0   # both learned
                  and close
                  and abs(ra - ta) < 0.08)
        verdict = ("round-0 exact; both learn; endpoints matched within "
                   "dropout noise" if ok
                   else "MISMATCH beyond dropout-noise criteria")
    protocol = {"users": users, "samples_per_user": samples,
                "batch_size": batch, "client_lr": lr,
                "rounds": rounds, "classes": classes,
                "local_steps_per_round": 1,
                "full_participation": True,
                "identical_init": True}
    if mode is not None:
        protocol["mode"] = mode
        protocol["strategy"] = rc["strategy"]
        protocol["dp_config"] = rc.get("dp_config")
        protocol["quant_thresh"] = rc["client_config"].get("quant_thresh")
        protocol["quant_bits"] = rc["client_config"].get("quant_bits")
        protocol["criteria"] = MODES[mode]["criteria"]
    return {
        "task": f"{task}+{mode}" if mode else task,
        "protocol": protocol,
        "rounds_compared": len(traj),
        "max_abs_diff_val_loss": max_dl,
        "max_abs_diff_val_acc": max_da,
        "ok": ok,
        "verdict": verdict,
        "final": traj[-1] if traj else None,
        "trajectory": traj,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tasks", default="lr,cnn,lstm,gru")
    ap.add_argument("--rounds", type=int, default=None,
                    help="override every task's round count "
                         "(default: per-task, see ROUNDS_BY_TASK)")
    ap.add_argument("--scratch", default="/tmp/parity_scratch")
    ap.add_argument("--out", default=os.path.join(REPO, "PARITY.json"))
    ap.add_argument("--merge", action="store_true",
                    help="update only --tasks entries in an existing "
                         "--out instead of overwriting the whole file")
    args = ap.parse_args()

    os.makedirs(args.scratch, exist_ok=True)
    results = {}
    if args.merge and os.path.exists(args.out):
        with open(args.out) as fh:
            results = json.load(fh)
    for task in args.tasks.split(","):
        task = task.strip()
        if task in MODES:  # extension mode riding a deterministic base
            results[task] = run_task(MODES[task].get("base", "lr"),
                                     args.rounds, args.scratch, mode=task)
        else:
            results[task] = run_task(task, args.rounds, args.scratch)
        r = results[task]
        print(f"[parity:{task}] rounds={r['rounds_compared']} "
              f"max|dloss|={r['max_abs_diff_val_loss']} "
              f"max|dacc|={r['max_abs_diff_val_acc']} ok={r['ok']}")
        # write after EVERY task: a flaky later task must not lose the
        # finished families of a long multi-task run
        with open(args.out, "w") as fh:
            json.dump(results, fh, indent=1)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
