"""Cross-framework parity harness: the ACTUAL reference (torch, mounted
read-only at /root/reference) vs msrflute_tpu on identical synthetic user
blobs, identical initial weights, matched hyperparameters.

Round-by-round val loss/acc trajectories are compared per task and written
to PARITY.json.  This is the strongest accuracy-parity evidence obtainable
with zero egress (real datasets unfetchable): both frameworks run their own
full federated stacks — reference thread-mode single process
(``core/federated.py:634-676``), msrflute_tpu its jitted SPMD round — and
must produce the same numbers.

Design notes:
- The reference runs from a symlink scratch tree (its plugin loaders
  resolve ``experiments/<task>`` against cwd; /root/reference is read-only
  so adapters are injected via the tree, never written there).
- Adapter tasks (tools/parity/adapters/) re-export the reference's own
  model/dataloader classes, adding only json-path loading.
- Identical init: one numpy weight set is written as a torch state_dict
  for the reference (``model_config.pretrained_model_path``,
  ``utils/utils.py:486-494``) and as a params-pytree msgpack for
  msrflute_tpu (same config key).  Layout conversions: torch Linear
  [out,in] -> flax kernel [in,out]; torch Conv [out,in,kh,kw] -> flax
  [kh,kw,in,out]; the CNN's flatten bridge permutes CHW->HWC flat order.
- Determinism: full participation (K == pool), one local epoch, one batch
  per client (batch_size >= samples/user), plain SGD both sides -> the
  trajectory is RNG-free except CNN dropout (LR is compared strictly;
  CNN by round-0 exactness + both-learned + matched endpoints, since
  dropout RNG time-offsets make pointwise mid-trajectory bands
  meaningless during steep descent).
- Images are stored pre-transposed for the reference (its __getitem__
  applies ``.T``, ``experiments/cv_lr_mnist/dataloaders/dataset.py:34``)
  and un-transposed for msrflute_tpu, so both models see the same tensors.

Usage: python tools/parity/run_parity.py [--tasks lr,cnn] [--rounds 20]
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
REFERENCE = "/root/reference"
ADAPTERS = os.path.join(REPO, "tools", "parity", "adapters")


# ----------------------------------------------------------------------
# synthetic blobs
# ----------------------------------------------------------------------
def gen_blob(rng, users, samples, shape, classes, sep=2.0, means=None):
    """Class-structured gaussian data: learnable but not trivial.

    Pass the same ``means`` for train and val: a fresh draw per split
    would make validation distributionally unrelated to training and pin
    val accuracy at chance regardless of learning.
    """
    if means is None:
        means = rng.normal(size=(classes,) + shape).astype(np.float32)
    out = {"users": [], "num_samples": [], "user_data": {},
           "user_data_label": {}}
    for u in range(users):
        y = rng.integers(0, classes, size=(samples,))
        x = (sep * means[y]
             + rng.normal(size=(samples,) + shape)).astype(np.float32)
        name = f"{u:04d}"
        out["users"].append(name)
        out["num_samples"].append(samples)
        out["user_data"][name] = {"x": x}
        out["user_data_label"][name] = y.astype(np.int64)
    return out


def write_blob(blob, path, transpose_images=False):
    def conv(x):
        x = np.asarray(x)
        if transpose_images and x.ndim == 3:  # [N, H, W] -> stored .T'd
            x = np.swapaxes(x, 1, 2)
        return x.tolist()

    js = {
        "users": blob["users"],
        "num_samples": blob["num_samples"],
        "user_data": {u: {"x": conv(d["x"])}
                      for u, d in blob["user_data"].items()},
        "user_data_label": {u: np.asarray(l).tolist()
                            for u, l in blob["user_data_label"].items()},
    }
    with open(path, "w") as fh:
        json.dump(js, fh)


def _markov_stream(rng, length, vocab, trans, noise):
    """One noisy-Markov token stream (ids 1..vocab-1): next id is
    ``trans[cur]`` with prob 1-noise, else uniform — the shared
    synthetic-language kernel of the lstm and gru blobs."""
    stream = np.empty(length, np.int64)
    stream[0] = rng.integers(1, vocab)
    for t in range(length - 1):
        stream[t + 1] = (rng.integers(1, vocab)
                         if rng.random() < noise
                         else trans[stream[t] - 1])
    return stream


def gen_lstm_blob(rng, users, samples, seq_len, vocab=90, trans=None,
                  noise=0.15):
    """Char sequences from a noisy deterministic next-char rule: with
    prob ``1-noise`` the next char is ``trans[cur]`` (a fixed random
    permutation of 1..vocab-1), else uniform — learnable structure for a
    next-char LSTM, never emitting the pad id 0 (so every target position
    is real and token- vs sequence-weighted metric aggregation coincide
    exactly across the two frameworks).  ``x`` is the stream's first L
    chars, ``y`` the next-char targets (the fed_shakespeare explicit-
    target blob shape).  Pass the same ``trans`` for train and val."""
    if trans is None:
        trans = rng.permutation(np.arange(1, vocab))
    out = {"users": [], "num_samples": [], "user_data": {},
           "user_data_label": {}}
    for u in range(users):
        xs, ys = [], []
        for _ in range(samples):
            stream = _markov_stream(rng, seq_len + 1, vocab, trans, noise)
            xs.append(stream[:seq_len])
            ys.append(stream[1:])
        name = f"{u:04d}"
        out["users"].append(name)
        out["num_samples"].append(samples)
        out["user_data"][name] = {"x": np.stack(xs)}
        out["user_data_label"][name] = np.stack(ys)
    return out


def gen_gru_blob(rng, users, seq_len, vocab=60, trans=None, noise=0.15):
    """nlg_gru-shaped blob: ONE word-id utterance per user (the
    reference's DynamicBatchSampler shuffles multi-utterance users with
    a wallclock-seeded epoch, so only 1 utt/user is order-deterministic;
    its frames budget == max_num_words then yields exactly one batch).
    Utterances are WORD STRINGS ("w<id>", all in-vocab) — the reference
    DatasetConfig has no ``preencoded`` field, so both frameworks
    tokenize through the same vocab file (case-backoff is a no-op for
    in-vocab words).  Ids stay in 1..vocab-1 (0 is the unk id the
    OOV-rejecting accuracy penalizes; never emitting it keeps both
    accuracy definitions trivially aligned), full length (no padding
    anywhere)."""
    if trans is None:
        trans = rng.permutation(np.arange(1, vocab))
    out = {"users": [], "num_samples": [], "user_data": {}}
    for u in range(users):
        stream = _markov_stream(rng, seq_len, vocab, trans, noise)
        name = f"{u:04d}"
        out["users"].append(name)
        out["num_samples"].append(1)
        out["user_data"][name] = {"x": [[f"w{i}" for i in stream]]}
    return out


def write_gru_blob(blob, path):
    with open(path, "w") as fh:
        json.dump(blob, fh)


def write_vocab(path, vocab):
    """Plain-txt vocab (one word per line): line index i maps word
    "w<i>" to id i in BOTH frameworks' loaders (nlg_gru utils
    ``load_vocab`` and ``msrflute_tpu.data.featurize.load_vocab``) —
    the vocab is load-bearing, since both sides tokenize the string
    blobs through it."""
    with open(path, "w") as fh:
        for i in range(vocab):
            fh.write(f"w{i}\n")


# ----------------------------------------------------------------------
# identical initial weights
# ----------------------------------------------------------------------
def lr_init(rng, input_dim=784, classes=10):
    scale = 1.0 / np.sqrt(input_dim)
    return {
        "w": rng.uniform(-scale, scale,
                         size=(classes, input_dim)).astype(np.float32),
        "b": rng.uniform(-scale, scale, size=(classes,)).astype(np.float32),
    }


def cnn_init(rng, classes=62):
    def kaiming(shape, fan_in):
        # torch kaiming_uniform_(a=sqrt(5)) default: bound = sqrt(6/((1+5)fan_in))
        bound = np.sqrt(6.0 / (6.0 * fan_in))
        return rng.uniform(-bound, bound, size=shape).astype(np.float32)

    def bias(shape, fan_in):
        bound = 1.0 / np.sqrt(fan_in)
        return rng.uniform(-bound, bound, size=shape).astype(np.float32)

    return {
        "conv1_w": kaiming((32, 1, 3, 3), 9), "conv1_b": bias((32,), 9),
        "conv2_w": kaiming((64, 32, 3, 3), 288), "conv2_b": bias((64,), 288),
        "fc1_w": kaiming((128, 9216), 9216), "fc1_b": bias((128,), 9216),
        "fc2_w": kaiming((classes, 128), 128), "fc2_b": bias((classes,), 128),
    }


def lstm_init(rng, vocab=90, embed=8, hidden=256):
    """torch-default init for the fed_shakespeare RNN: Embedding N(0,1)
    with the padding row zeroed, every nn.LSTM weight/bias
    uniform(-1/sqrt(H), 1/sqrt(H)), Linear kaiming-uniform(a=sqrt(5))
    (== uniform(-1/sqrt(fan_in), 1/sqrt(fan_in))) + matching bias."""
    k = 1.0 / np.sqrt(hidden)

    def u(shape):
        return rng.uniform(-k, k, size=shape).astype(np.float32)

    emb = rng.normal(size=(vocab, embed)).astype(np.float32)
    emb[0] = 0.0  # nn.Embedding(padding_idx=0) zeroes the pad row
    init = {"emb": emb}
    for layer, in_dim in ((0, embed), (1, hidden)):
        init[f"w_ih_l{layer}"] = u((4 * hidden, in_dim))
        init[f"w_hh_l{layer}"] = u((4 * hidden, hidden))
        init[f"b_ih_l{layer}"] = u((4 * hidden,))
        init[f"b_hh_l{layer}"] = u((4 * hidden,))
    bound = 1.0 / np.sqrt(hidden)
    init["fc_w"] = rng.uniform(-bound, bound,
                               size=(vocab, hidden)).astype(np.float32)
    init["fc_b"] = rng.uniform(-bound, bound,
                               size=(vocab,)).astype(np.float32)
    return init


def gru_init(rng, vocab=60, embed=16, hidden=64):
    """torch-default init for the nlg_gru GRU: embedding table
    uniform(±sqrt(3/E)) (Embedding.__init__), unembedding bias zeros,
    both GRU2 Linears kaiming-uniform(a=sqrt(5)) == uniform(±1/sqrt(in))
    with matching bias bounds, squeeze Linear (no bias) ditto."""
    def lin(out_dim, in_dim):
        b = 1.0 / np.sqrt(in_dim)
        return (rng.uniform(-b, b, size=(out_dim, in_dim)).astype(np.float32),
                rng.uniform(-b, b, size=(out_dim,)).astype(np.float32))

    delta = np.sqrt(3.0 / embed)
    table = rng.uniform(-delta, delta,
                        size=(vocab, embed)).astype(np.float32)
    w_ih, b_ih = lin(3 * hidden, embed)
    w_hh, b_hh = lin(3 * hidden, hidden)
    sq_w, _ = lin(embed, hidden)
    return {"table": table,
            "unembedding_bias": np.zeros((vocab,), np.float32),
            "w_ih": w_ih, "b_ih": b_ih, "w_hh": w_hh, "b_hh": b_hh,
            "squeeze": sq_w}


def save_torch_gru(init, path):
    import torch
    # the GRU model's submodules hang directly off self (no .net wrapper,
    # unlike the LR/CNN/RNN task classes)
    sd = {"embedding.table": torch.tensor(init["table"]),
          "embedding.unembedding_bias": torch.tensor(
              init["unembedding_bias"]),
          "rnn.w_ih.weight": torch.tensor(init["w_ih"]),
          "rnn.w_ih.bias": torch.tensor(init["b_ih"]),
          "rnn.w_hh.weight": torch.tensor(init["w_hh"]),
          "rnn.w_hh.bias": torch.tensor(init["b_hh"]),
          "squeeze.weight": torch.tensor(init["squeeze"])}
    torch.save(sd, path)


def save_flax_gru(init, path):
    """GRU2 keeps the three gates (r, i, n) stacked in one [3H, in]
    Linear on each side — our _ConvexGRUCell mirrors that layout exactly
    (same order, jnp.split), so only the Linear [out,in] -> flax [in,out]
    transposes apply."""
    from flax import serialization
    params = {
        "embedding": init["table"],
        "unembedding_bias": init["unembedding_bias"],
        "Scan_ConvexGRUCell_0": {
            "w_ih": {"kernel": init["w_ih"].T, "bias": init["b_ih"]},
            "w_hh": {"kernel": init["w_hh"].T, "bias": init["b_hh"]},
        },
        "squeeze": {"kernel": init["squeeze"].T},
    }
    with open(path, "wb") as fh:
        fh.write(serialization.msgpack_serialize(
            serialization.to_state_dict(params)))


def save_torch_lr(init, path):
    import torch
    sd = {"net.linear.weight": torch.tensor(init["w"]),
          "net.linear.bias": torch.tensor(init["b"])}
    torch.save(sd, path)


def save_torch_cnn(init, path):
    import torch
    sd = {
        "net.conv2d_1.weight": torch.tensor(init["conv1_w"]),
        "net.conv2d_1.bias": torch.tensor(init["conv1_b"]),
        "net.conv2d_2.weight": torch.tensor(init["conv2_w"]),
        "net.conv2d_2.bias": torch.tensor(init["conv2_b"]),
        "net.linear_1.weight": torch.tensor(init["fc1_w"]),
        "net.linear_1.bias": torch.tensor(init["fc1_b"]),
        "net.linear_2.weight": torch.tensor(init["fc2_w"]),
        "net.linear_2.bias": torch.tensor(init["fc2_b"]),
    }
    torch.save(sd, path)


def save_torch_lstm(init, path):
    import torch
    sd = {"net.embeddings.weight": torch.tensor(init["emb"]),
          "net.fc.weight": torch.tensor(init["fc_w"]),
          "net.fc.bias": torch.tensor(init["fc_b"])}
    for layer in (0, 1):
        for name in ("w_ih", "w_hh", "b_ih", "b_hh"):
            sd[f"net.lstm.{name.replace('w_', 'weight_').replace('b_', 'bias_')}_l{layer}"] = \
                torch.tensor(init[f"{name}_l{layer}"])
    torch.save(sd, path)


def save_flax_lstm(init, path, hidden=256):
    """torch nn.LSTM -> flax OptimizedLSTMCell: torch stacks the four
    gates (i, f, g, o) along dim 0 of weight_ih/weight_hh ([4H, in]) with
    two bias vectors (bias_ih + bias_hh, always summed in the cell); flax
    names per-gate Dense blocks — input kernels ``i{g}`` [in, H] without
    bias, hidden kernels ``h{g}`` [H, H] carrying the single bias."""
    from flax import serialization
    H = hidden
    params = {"Embed_0": {"embedding": init["emb"]},
              "Dense_0": {"kernel": init["fc_w"].T, "bias": init["fc_b"]}}
    for layer in (0, 1):
        cell = {}
        for k, g in enumerate("ifgo"):
            sl = slice(k * H, (k + 1) * H)
            cell[f"i{g}"] = {"kernel": init[f"w_ih_l{layer}"][sl].T}
            cell[f"h{g}"] = {"kernel": init[f"w_hh_l{layer}"][sl].T,
                             "bias": (init[f"b_ih_l{layer}"][sl]
                                      + init[f"b_hh_l{layer}"][sl])}
        params[f"OptimizedLSTMCell_{layer}"] = cell
    with open(path, "wb") as fh:
        fh.write(serialization.msgpack_serialize(
            serialization.to_state_dict(params)))


def save_flax_lr(init, path):
    from flax import serialization
    params = {"Dense_0": {"kernel": init["w"].T, "bias": init["b"]}}
    with open(path, "wb") as fh:
        fh.write(serialization.msgpack_serialize(
            serialization.to_state_dict(params)))


def save_flax_cnn(init, path):
    from flax import serialization
    # conv: [out,in,kh,kw] -> [kh,kw,in,out]
    # fc1 bridge: torch flattens NCHW [64,12,12] C-major; flax flattens
    # NHWC [12,12,64] HW-major -> permute fc1's input axis accordingly
    fc1 = init["fc1_w"].reshape(128, 64, 12, 12).transpose(0, 2, 3, 1)
    fc1 = fc1.reshape(128, 9216)
    params = {
        "Conv_0": {"kernel": init["conv1_w"].transpose(2, 3, 1, 0),
                   "bias": init["conv1_b"]},
        "Conv_1": {"kernel": init["conv2_w"].transpose(2, 3, 1, 0),
                   "bias": init["conv2_b"]},
        "Dense_0": {"kernel": fc1.T, "bias": init["fc1_b"]},
        "Dense_1": {"kernel": init["fc2_w"].T, "bias": init["fc2_b"]},
    }
    with open(path, "wb") as fh:
        fh.write(serialization.msgpack_serialize(
            serialization.to_state_dict(params)))


# ----------------------------------------------------------------------
# configs
# ----------------------------------------------------------------------
GRU_DIMS = {"vocab_size": 60, "embed_dim": 16, "hidden_dim": 64}


def ref_config(task, rounds, users, batch, lr, init_path, outdim):
    model = {"model_type": {"lr": "LR", "cnn": "CNN", "lstm": "RNN",
                            "gru": "GRU"}[task],
             "model_folder": f"experiments/parity_{task}/model.py",
             "pretrained_model_path": init_path}
    if task == "lr":
        model.update({"input_dim": 784, "output_dim": outdim})
    elif task == "gru":
        model.update(GRU_DIMS)
    return {
        "model_config": model,
        "dp_config": {"enable_local_dp": False},
        "privacy_metrics_config": {"apply_metrics": False},
        "strategy": "FedAvg",
        "server_config": {
            "wantRL": False, "resume_from_checkpoint": False,
            "do_profiling": False,
            "optimizer_config": {"type": "sgd", "lr": 1.0},
            "annealing_config": {"type": "step_lr", "step_interval": "epoch",
                                 "gamma": 1.0, "step_size": 1000},
            "val_freq": 1, "rec_freq": 100000,
            "initial_val": True, "initial_rec": False,
            "max_iteration": rounds,
            "num_clients_per_iteration": users,
            "data_config": {
                "val": {"batch_size": 4096, "val_data": "val.json"},
                "test": {"batch_size": 4096, "test_data": "val.json"},
            },
            "type": "model_optimization",
            "aggregate_median": "softmax",
            "initial_lr_client": lr, "lr_decay_factor": 1.0,
            "weight_train_loss": "train_loss",
            "best_model_criterion": "loss",
            "fall_back_to_best_model": False, "softmax_beta": 1.0,
        },
        "client_config": {
            "do_profiling": False, "ignore_subtask": False,
            "data_config": {
                "train": {"batch_size": batch,
                          "list_of_train_data": "train.json",
                          "desired_max_samples": 100000},
            },
            "optimizer_config": {"type": "sgd", "lr": lr},
            "type": "optimization",
        },
    }


def tpu_config(task, rounds, users, batch, lr, init_path, outdim):
    model = {"model_type": {"lr": "LR", "cnn": "CNN", "lstm": "LSTM",
                            "gru": "GRU"}[task],
             "pretrained_model_path": init_path}
    if task == "lr":
        model.update({"input_dim": 784, "num_classes": outdim,
                      "sigmoid_output": True})  # the reference LR quirk
    elif task == "lstm":
        # outdim carries seq_len for the lstm task (vocab is the
        # reference's hardcoded 90/8/256 architecture)
        model.update({"vocab_size": 90, "embed_dim": 8, "hidden_dim": 256,
                      "seq_len": outdim})
    elif task == "gru":
        model.update(dict(GRU_DIMS, max_num_words=outdim))
    else:
        model.update({"num_classes": outdim})
    return {
        "model_config": model,
        "strategy": "FedAvg",
        "server_config": {
            "optimizer_config": {"type": "sgd", "lr": 1.0},
            "annealing_config": {"type": "step_lr", "step_interval": "epoch",
                                 "gamma": 1.0, "step_size": 1000},
            "val_freq": 1, "rec_freq": 100000,
            "initial_val": True, "initial_rec": False,
            "max_iteration": rounds,
            "num_clients_per_iteration": users,
            "data_config": {
                "val": {"batch_size": 4096, "val_data": "val.json"},
                "test": {"batch_size": 4096, "test_data": "val.json"},
            },
            "type": "model_optimization",
            "initial_lr_client": lr, "lr_decay_factor": 1.0,
            "best_model_criterion": "loss",
        },
        "client_config": {
            "data_config": {
                "train": {"batch_size": batch,
                          "list_of_train_data": "train.json"},
            },
            "optimizer_config": {"type": "sgd", "lr": lr},
            "type": "optimization",
        },
    }


# ----------------------------------------------------------------------
# runners
# ----------------------------------------------------------------------
def build_ref_tree(scratch):
    """Symlink tree so the reference runs with our adapter experiments
    without writing to the read-only mount."""
    tree = os.path.join(scratch, "refrun")
    shutil.rmtree(tree, ignore_errors=True)
    os.makedirs(os.path.join(tree, "experiments"))
    for name in ("core", "utils", "extensions", "e2e_trainer.py"):
        os.symlink(os.path.join(REFERENCE, name), os.path.join(tree, name))
    for name in os.listdir(os.path.join(REFERENCE, "experiments")):
        os.symlink(os.path.join(REFERENCE, "experiments", name),
                   os.path.join(tree, "experiments", name))
    for task in ("parity_lr", "parity_cnn", "parity_lstm", "parity_gru"):
        os.symlink(os.path.join(ADAPTERS, task),
                   os.path.join(tree, "experiments", task))
    return tree


def run_reference(tree, cfg_path, data_dir, out_dir, task, metrics_out):
    """Run the reference in its REAL 2-process mode (server rank0 + worker
    rank1, gloo): the distributed path implements the documented FedAvg
    math.  (Thread mode, ``core/federated.py:683-707``, is avoided on
    purpose: on CPU ``tensor.to('cpu')`` is a no-copy alias, so its
    aggregate double-counts and the server steps from the last client's
    in-place-trained weights — measured in this harness, round-1 update
    ``0.1*g_last + 2*avg`` instead of ``avg``.  On GPU both artifacts
    disappear, so the published numbers are unaffected — but it is not the
    math to compare against.)"""
    env = dict(
        os.environ,
        REF_METRICS_OUT=metrics_out,
        PYTHONPATH=os.pathsep.join(
            [tree, os.path.join(REPO, "tools", "ref_shims")]),
        CUDA_VISIBLE_DEVICES="",
    )
    # PID-derived rendezvous port: concurrent parity runs (pytest + manual)
    # must not collide on a fixed port
    port = 20000 + os.getpid() % 20000
    cmd = [sys.executable, "-m", "torch.distributed.run",
           f"--nproc_per_node=2", f"--master-port={port}",
           os.path.join(REPO, "tools", "parity", "ref_launch.py"),
           "-dataPath", data_dir,
           "-outputPath", out_dir, "-config", cfg_path,
           "-task", task, "-backend", "gloo"]
    proc = subprocess.run(cmd, cwd=tree, env=env, capture_output=True,
                          text=True)
    if proc.returncode != 0:
        sys.stderr.write(proc.stdout[-4000:] + "\n" + proc.stderr[-6000:])
        raise RuntimeError(f"reference trainer failed rc={proc.returncode}")
    # Vals appear strictly in round order but the "Current iteration" marker
    # flushes late (end-of-round metrics_payload), so align by ORDER: with
    # initial_val on, the j-th val record is the state after j rounds.
    rounds = {}
    j = {"Val loss": 0, "Val acc": 0}
    with open(metrics_out) as fh:
        for line in fh:
            rec = json.loads(line)
            name = rec["name"]
            if name in j:
                rounds.setdefault(j[name], {})[name] = float(rec["value"])
                j[name] += 1
    return rounds


def run_msrflute(cfg_path, data_dir, out_dir, task):
    env = dict(
        os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    cmd = [sys.executable, os.path.join(REPO, "e2e_trainer.py"),
           "-config", cfg_path, "-dataPath", data_dir,
           "-outputPath", out_dir, "-task", task]
    proc = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True)
    if proc.returncode != 0:
        sys.stderr.write(proc.stdout[-4000:] + "\n" + proc.stderr[-6000:])
        raise RuntimeError(f"msrflute_tpu trainer failed rc={proc.returncode}")
    rounds = {}
    with open(os.path.join(out_dir, "log", "metrics.jsonl")) as fh:
        for line in fh:
            rec = json.loads(line)
            if rec.get("name") in ("Val loss", "Val acc"):
                rounds.setdefault(int(rec["step"]), {})[rec["name"]] = \
                    float(rec["value"])
    return rounds


# ----------------------------------------------------------------------
# orchestration
# ----------------------------------------------------------------------
TASKS = {
    # task: (shape, model classes, users, samples/user, batch, client_lr,
    #        data classes)
    # CNN: the reference model hardcodes 62 outputs (CNN_DropOut(False)),
    # but the synthetic blob only uses the first 10 labels with wide
    # separation — learnable at a dropout-gentle lr, so both trajectories
    # visibly descend instead of hovering at chance or diverging.
    "lr": ((784,), 10, 16, 32, 64, 0.1, 10),
    "cnn": ((28, 28), 62, 8, 48, 64, 0.05, 10),
    # LSTM: shape slot carries seq_len; "classes" is the model's hardcoded
    # vocab (90).  No dropout -> the trajectory is fully deterministic and
    # compared strictly, like LR (modulo deeper f32 recurrence noise).
    # lr=4.0: the protocol is exact full-batch SGD (1 batch/client, full
    # participation), which is stable at large lr and needs it — the
    # next-char rule only becomes learnable within ~100 rounds there
    # (probed offline; see ROUNDS_OVERRIDE).
    "lstm": ((24,), 90, 8, 16, 16, 4.0, None),
    # GRU (nlg_gru): shape = seq_len (== max_num_words), classes = vocab
    # (dims in GRU_DIMS); ONE utterance per user — the reference's
    # DynamicBatchSampler seeds its shuffle from wallclock randomness,
    # so only single-batch users are order-deterministic (its frames
    # budget == max_num_words then yields exactly one batch).  lr=1.0 is
    # stable full-batch (4.0 diverges — probed offline).
    # 48 users x 11 transitions must cover the 59-way next-word rule, or
    # val loss bottoms out early and rises (measured at 16 users: exact
    # tracking but the "loss halved" learning criterion fails on
    # overfitting, not on mismatch)
    "gru": ((12,), 60, 48, 1, 4, 1.0, None),
}

# per-task default round counts, used when the caller leaves --rounds
# unset: single-local-step protocols can need more rounds to show
# learning (the reference runs exactly one epoch per round and
# multi-batch rounds would be shuffle-order-incomparable).  An explicit
# --rounds always wins (smoke tests pass --rounds 3).
DEFAULT_ROUNDS = 20
ROUNDS_BY_TASK = {"lstm": 100, "gru": 100}


def run_task(task, rounds, scratch):
    shape, classes, users, samples, batch, lr, data_classes = TASKS[task]
    if rounds is None:
        rounds = ROUNDS_BY_TASK.get(task, DEFAULT_ROUNDS)
    rng = np.random.default_rng(7)
    work = os.path.join(scratch, task)
    shutil.rmtree(work, ignore_errors=True)
    data_ref = os.path.join(work, "data_ref")
    data_tpu = os.path.join(work, "data_tpu")
    os.makedirs(data_ref)
    os.makedirs(data_tpu)

    if task == "lstm":
        seq_len = shape[0]
        trans = rng.permutation(np.arange(1, classes))
        train = gen_lstm_blob(rng, users, samples, seq_len, vocab=classes,
                              trans=trans)
        val = gen_lstm_blob(rng, 4, 32, seq_len, vocab=classes, trans=trans)
        # int sequences need no layout conversion between the frameworks
        for blob, name in ((train, "train.json"), (val, "val.json")):
            write_blob(blob, os.path.join(data_ref, name))
            write_blob(blob, os.path.join(data_tpu, name))
        init = lstm_init(rng, vocab=classes)
        save_torch_lstm(init, os.path.join(work, "init.pt"))
        save_flax_lstm(init, os.path.join(work, "init.msgpack"))
    elif task == "gru":
        seq_len = shape[0]
        trans = rng.permutation(np.arange(1, classes))
        train = gen_gru_blob(rng, users, seq_len, vocab=classes,
                             trans=trans)
        val = gen_gru_blob(rng, 16, seq_len, vocab=classes, trans=trans)
        for blob, name in ((train, "train.json"), (val, "val.json")):
            write_gru_blob(blob, os.path.join(data_ref, name))
            write_gru_blob(blob, os.path.join(data_tpu, name))
        write_vocab(os.path.join(work, "vocab.txt"), classes)
        init = gru_init(rng, vocab=classes, embed=GRU_DIMS["embed_dim"],
                        hidden=GRU_DIMS["hidden_dim"])
        save_torch_gru(init, os.path.join(work, "init.pt"))
        save_flax_gru(init, os.path.join(work, "init.msgpack"))
    else:
        means = rng.normal(size=(data_classes,) + shape).astype(np.float32)
        train = gen_blob(rng, users, samples, shape, data_classes, sep=3.0,
                         means=means)
        val = gen_blob(rng, 4, 64, shape, data_classes, sep=3.0, means=means)
        # the reference __getitem__ transposes images; pre-swap its copy so
        # both frameworks train on identical tensors
        for blob, name in ((train, "train.json"), (val, "val.json")):
            write_blob(blob, os.path.join(data_ref, name),
                       transpose_images=True)
            write_blob(blob, os.path.join(data_tpu, name),
                       transpose_images=False)

        if task == "lr":
            init = lr_init(rng, 784, classes)
            save_torch_lr(init, os.path.join(work, "init.pt"))
            save_flax_lr(init, os.path.join(work, "init.msgpack"))
        else:
            init = cnn_init(rng, classes)
            save_torch_cnn(init, os.path.join(work, "init.pt"))
            save_flax_cnn(init, os.path.join(work, "init.msgpack"))

    import yaml
    tree = build_ref_tree(scratch)
    outdim = shape[0] if task in ("lstm", "gru") else classes  # seq_len
    rc = ref_config(task, rounds, users, batch, lr,
                    os.path.join(work, "init.pt"), outdim)
    tc = tpu_config(task, rounds, users, batch, lr,
                    os.path.join(work, "init.msgpack"), outdim)
    if task == "gru":
        # the nlg_gru loaders read their knobs from the per-split data
        # blocks: plain-txt vocab (absolute path), frames budget ==
        # max_num_words (-> one utterance per batch), preencoded int rows
        gru_keys = {"vocab_dict": os.path.join(work, "vocab.txt"),
                    "max_num_words": shape[0], "pin_memory": False,
                    "unsorted_batch": True}
        rc["server_config"]["data_config"]["val"].update(gru_keys)
        rc["server_config"]["data_config"]["test"].update(gru_keys)
        rc["client_config"]["data_config"]["train"].update(gru_keys)
        # our side tokenizes through the SAME vocab file
        tc["model_config"]["vocab_dict"] = os.path.join(work, "vocab.txt")
    ref_cfg = os.path.join(work, "ref.yaml")
    tpu_cfg = os.path.join(work, "tpu.yaml")
    with open(ref_cfg, "w") as fh:
        yaml.safe_dump(rc, fh)
    with open(tpu_cfg, "w") as fh:
        yaml.safe_dump(tc, fh)

    print(f"[parity:{task}] running reference (torch, 2-process gloo)...")
    ref = run_reference(tree, ref_cfg, data_ref,
                        os.path.join(work, "out_ref"), f"parity_{task}",
                        os.path.join(work, "ref_metrics.jsonl"))
    print(f"[parity:{task}] running msrflute_tpu (8-dev virtual cpu mesh)...")
    tpu = run_msrflute(tpu_cfg, data_tpu, os.path.join(work, "out_tpu"),
                       f"parity_{task}")

    common = sorted(set(ref) & set(tpu))
    traj = []
    for r in common:
        row = {"round": r}
        for key in ("Val loss", "Val acc"):
            rv, tv = ref[r].get(key), tpu[r].get(key)
            row[key] = {"reference": rv, "msrflute_tpu": tv,
                        "abs_diff": (abs(rv - tv)
                                     if rv is not None and tv is not None
                                     else None)}
        traj.append(row)
    diffs_loss = [row["Val loss"]["abs_diff"] for row in traj
                  if row["Val loss"]["abs_diff"] is not None]
    diffs_acc = [row["Val acc"]["abs_diff"] for row in traj
                 if row["Val acc"]["abs_diff"] is not None]
    max_dl = max(diffs_loss) if diffs_loss else None
    max_da = max(diffs_acc) if diffs_acc else None
    if task == "lr":
        # fully deterministic protocol: must be trajectory-exact
        ok = max_dl is not None and max_dl < 1e-4 and max_da == 0.0
        verdict = ("trajectory-exact (float32 accumulation noise only)"
                   if ok else "MISMATCH beyond float noise")
    elif task in ("lstm", "gru"):
        # no dropout -> fully deterministic, but chaotically SENSITIVE:
        # measured on this protocol (committed PARITY.json), the sides
        # agree to < 1e-3 for the first ~30 rounds (pure f32
        # accumulation-order noise), then the steep-descent phase
        # amplifies that noise exponentially — pointwise gaps transiently
        # reach O(1) mid-descent (1.45 at round 67 in the committed run,
        # where the two sides cross the cliff a few rounds apart) — and
        # the gap CONTRACTS again as both converge (0.08 by round 100).
        # That grow-then-recontract shape is the signature of trajectory
        # sensitivity, not of a semantic difference (a wrong lr or
        # denominator would drift proportionally from round 1).  Honest
        # criteria, mirroring the CNN rationale: the early phase is
        # strictly exact, both sides learn the next-char rule, and the
        # endpoints match.
        early = [row["Val loss"]["abs_diff"] for row in traj[:26]
                 if row["Val loss"]["abs_diff"] is not None]
        ref0 = traj[0]["Val loss"]["reference"] if traj else None
        a0r = traj[0]["Val acc"]["reference"] if traj else None
        a0t = traj[0]["Val acc"]["msrflute_tpu"] if traj else None
        fin = traj[-1] if traj else None
        rl = (fin or {}).get("Val loss", {}).get("reference")
        tl = (fin or {}).get("Val loss", {}).get("msrflute_tpu")
        ra = (fin or {}).get("Val acc", {}).get("reference")
        ta = (fin or {}).get("Val acc", {}).get("msrflute_tpu")
        ok = False
        if early and None not in (ref0, a0r, a0t, rl, tl, ra, ta):
            # "both learned" must respect the task's entropy floor: the
            # noisy next-token rules have irreducible CE (noise entropy +
            # the unpredictable first token), so demand a clear loss drop
            # AND a decisive accuracy gain rather than an arbitrary
            # loss-halving (measured: gru converges to ~2.3 from 4.1 at
            # 72% accuracy — halving is unreachable there by design)
            learned = (rl < 0.8 * ref0 and tl < 0.8 * ref0
                       and ra - a0r > 0.25 and ta - a0t > 0.25)
            ok = (max(early) < 5e-3
                  and learned
                  # absolute-or-relative: near-zero converged losses make
                  # a pure relative test divide by ~0 (CNN branch ditto)
                  and (abs(rl - tl) < 0.05
                       or abs(rl - tl) / max(rl, tl) < 0.1)
                  and abs(ra - ta) < 0.05)
        verdict = ("early-trajectory exact (f32 noise only); both learn "
                   "the rule; endpoints matched within chaotic-"
                   "sensitivity noise" if ok
                   else "MISMATCH beyond deterministic-sensitivity criteria")
    else:
        # CNN has torch/jax-incomparable dropout RNG, and during the steep
        # descent phase a small RNG-induced time offset yields large
        # pointwise loss gaps — so a max-abs-diff band is the wrong
        # metric.  The honest criteria: round 0 (dropout inactive) exact,
        # both trajectories actually LEARN (final loss well below round 0),
        # and the endpoints agree (relative loss diff + acc diff small).
        r0 = traj[0]["Val loss"]["abs_diff"] if traj else None
        fin = traj[-1] if traj else None
        ref0 = traj[0]["Val loss"]["reference"] if traj else None
        ok = False
        vals = ((fin or {}).get("Val loss", {}), (fin or {}).get("Val acc", {}))
        rl, tl = vals[0].get("reference"), vals[0].get("msrflute_tpu")
        ra, ta = vals[1].get("reference"), vals[1].get("msrflute_tpu")
        if None not in (r0, ref0, rl, tl, ra, ta):
            # endpoints agree: absolute OR relative — near-converged losses
            # (both ~1e-3) make a pure relative test meaningless
            close = (abs(rl - tl) < 0.05
                     or abs(rl - tl) / max(rl, tl) < 0.05)
            ok = (r0 < 1e-4
                  and rl < 0.8 * ref0 and tl < 0.8 * ref0   # both learned
                  and close
                  and abs(ra - ta) < 0.08)
        verdict = ("round-0 exact; both learn; endpoints matched within "
                   "dropout noise" if ok
                   else "MISMATCH beyond dropout-noise criteria")
    return {
        "task": task,
        "protocol": {"users": users, "samples_per_user": samples,
                     "batch_size": batch, "client_lr": lr,
                     "rounds": rounds, "classes": classes,
                     "local_steps_per_round": 1,
                     "full_participation": True,
                     "identical_init": True},
        "rounds_compared": len(traj),
        "max_abs_diff_val_loss": max_dl,
        "max_abs_diff_val_acc": max_da,
        "ok": ok,
        "verdict": verdict,
        "final": traj[-1] if traj else None,
        "trajectory": traj,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tasks", default="lr,cnn,lstm,gru")
    ap.add_argument("--rounds", type=int, default=None,
                    help="override every task's round count "
                         "(default: per-task, see ROUNDS_BY_TASK)")
    ap.add_argument("--scratch", default="/tmp/parity_scratch")
    ap.add_argument("--out", default=os.path.join(REPO, "PARITY.json"))
    ap.add_argument("--merge", action="store_true",
                    help="update only --tasks entries in an existing "
                         "--out instead of overwriting the whole file")
    args = ap.parse_args()

    os.makedirs(args.scratch, exist_ok=True)
    results = {}
    if args.merge and os.path.exists(args.out):
        with open(args.out) as fh:
            results = json.load(fh)
    for task in args.tasks.split(","):
        results[task] = run_task(task.strip(), args.rounds, args.scratch)
        r = results[task]
        print(f"[parity:{task}] rounds={r['rounds_compared']} "
              f"max|dloss|={r['max_abs_diff_val_loss']} "
              f"max|dacc|={r['max_abs_diff_val_acc']}")

    with open(args.out, "w") as fh:
        json.dump(results, fh, indent=1)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
