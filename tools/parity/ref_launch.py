"""Launcher for the reference e2e_trainer inside the parity scratch tree.

The reference's ``get_exp_dataloader`` (``utils/dataloaders_utils.py:9-23``)
swallows every import error behind a bare ``except`` and returns an unbound
loader — any adapter problem then surfaces 3 frames later as an unrelated
crash.  This launcher patches it to load the same path but let the real
traceback propagate, then runs e2e_trainer unchanged.
"""
import os
import sys
from importlib.machinery import SourceFileLoader

import utils.dataloaders_utils as du


def _get_exp_dataloader(task):
    path = os.path.join("experiments", task, "dataloaders", "dataloader.py")
    return SourceFileLoader("DataLoader", path).load_module().DataLoader


du.get_exp_dataloader = _get_exp_dataloader

sys.argv = ["e2e_trainer.py"] + sys.argv[1:]
import runpy  # noqa: E402

runpy.run_path("e2e_trainer.py", run_name="__main__")
