"""Launcher for the reference e2e_trainer inside the parity scratch tree.

The reference's ``get_exp_dataloader`` (``utils/dataloaders_utils.py:9-23``)
swallows every import error behind a bare ``except`` and returns an unbound
loader — any adapter problem then surfaces 3 frames later as an unrelated
crash.  This launcher patches it to load the same path but let the real
traceback propagate, then runs e2e_trainer unchanged.
"""
import os
import sys
from importlib.machinery import SourceFileLoader

import utils.dataloaders_utils as du


def _get_exp_dataloader(task):
    path = os.path.join("experiments", task, "dataloaders", "dataloader.py")
    return SourceFileLoader("DataLoader", path).load_module().DataLoader


du.get_exp_dataloader = _get_exp_dataloader

# PyTorch >= 2.6 defaults torch.load(weights_only=True), which rejects the
# numpy scalar the reference pickles for the personalization alpha
# (``torch.save(alpha, ...)``, core/client.py:442 — alpha_update returns an
# np.clip float64).  Allowlist the numpy globals so the reference's own
# save/load roundtrip works under the current torch.
import numpy as _np  # noqa: E402
import torch as _torch  # noqa: E402

_torch.serialization.add_safe_globals(
    [_np.dtype, _np.ndarray, _np._core.multiarray.scalar,
     _np._core.multiarray._reconstruct]
    + [getattr(_np.dtypes, n) for n in dir(_np.dtypes)
       if n.endswith("DType")])

sys.argv = ["e2e_trainer.py"] + sys.argv[1:]
import runpy  # noqa: E402

runpy.run_path("e2e_trainer.py", run_name="__main__")
