"""Parity-harness adapter task: re-exports the REFERENCE LR model class
unchanged (``experiments/cv_lr_mnist/model.py:23``) so the cross-framework
comparison trains the reference's own torch code, not a copy."""
from experiments.cv_lr_mnist.model import LR  # noqa: F401
