"""Parity adapter dataset: the reference cv_lr_mnist Dataset expects an
already-loaded blob dict (its path mode downloads MNIST, impossible with
zero egress) — this subclass adds json-path loading, everything else is the
reference class (``experiments/cv_lr_mnist/dataloaders/dataset.py``)."""
from experiments.cv_lr_mnist.dataloaders.dataset import Dataset as _RefDataset
from parity_blob import maybe_load


class Dataset(_RefDataset):
    def __init__(self, data, test_only=False, user_idx=0, **kwargs):
        super().__init__(maybe_load(data), test_only=test_only,
                         user_idx=user_idx, **kwargs)
