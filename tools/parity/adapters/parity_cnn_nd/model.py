"""Dropout-free variant of the CNN parity adapter (VERDICT r3 item 3).

Subclasses the reference's own CNN task class
(``experiments/cv_cnn_femnist/model.py:82``, net = FedML ``CNN_DropOut``)
and zeroes both dropout probabilities — ``torch.nn.Dropout(p=0)`` is the
identity, so the forward pass becomes fully deterministic and the
cross-framework comparison upgrades from endpoint-grade to
trajectory-exact.  The harness runs it with ``-task parity_cnn`` for
data loading; only ``model_folder`` points here.
"""
from experiments.parity_cnn.model import CNN as _CNN


class CNN(_CNN):
    def __init__(self, model_config):
        super().__init__(model_config)
        self.net.dropout_1.p = 0.0
        self.net.dropout_2.p = 0.0
