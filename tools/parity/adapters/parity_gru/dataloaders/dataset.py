"""Parity adapter dataset: the reference nlg_gru Dataset unchanged — it
already json-loads a str data path (``load_data``), and string
utterances go through the same vocab/case-backoff tokenization both
frameworks share."""
from experiments.nlg_gru.dataloaders.dataset import Dataset  # noqa: F401
