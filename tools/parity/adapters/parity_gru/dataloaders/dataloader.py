"""Parity adapter dataloader: the reference nlg_gru DataLoader unchanged
— its Dataset already json-loads a str data path, and the string
utterances tokenize through the shared vocab file (case-backoff is a
no-op for in-vocab words)."""
from experiments.nlg_gru.dataloaders.dataloader import DataLoader  # noqa: F401
