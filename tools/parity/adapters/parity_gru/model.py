"""Parity-harness adapter task: re-exports the REFERENCE nlg_gru GRU
model class unchanged (``experiments/nlg_gru/model.py:57``) so the
cross-framework comparison trains the reference's own torch code."""
from experiments.nlg_gru.model import GRU  # noqa: F401
