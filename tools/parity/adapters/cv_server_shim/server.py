"""Signature-current stand-in for the reference's PersonalizationServer.

``core/server.py:593-595`` hardcodes ``from experiments.cv.server import
PersonalizationServer``, but that class (``experiments/cv/server.py:10-17``)
predates OptimizationServer's current constructor (``single_worker`` et
al.) and crashes on instantiation — the reference's personalization mode
is broken out of the box (documented in docs/reference_quirks.md).  The
class adds NO behavior beyond calling super() with the stale argument
list, so a pass-through subclass is a faithful repair; the parity run's
symlink tree maps ``experiments/cv`` here (the real cv experiment's other
files are not used by the personalization-parity task)."""
from core.server import OptimizationServer


class PersonalizationServer(OptimizationServer):
    pass
