"""Parity-harness adapter task: re-exports the REFERENCE CNN model class
unchanged (``experiments/cv_cnn_femnist/model.py:82``) so the cross-framework
comparison trains the reference's own torch code, not a copy."""
from experiments.cv_cnn_femnist.model import CNN  # noqa: F401
