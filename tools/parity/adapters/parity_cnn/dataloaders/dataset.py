"""Parity adapter dataset: the reference cv_cnn_femnist Dataset expects an
already-loaded blob dict (its path mode downloads MNIST, impossible with
zero egress) — this subclass adds json-path loading, everything else is the
reference class (``experiments/cv_cnn_femnist/dataloaders/dataset.py``)."""
import json

import numpy as np

from experiments.cv_cnn_femnist.dataloaders.dataset import Dataset as _RefDataset


def maybe_load(data):
    """str path -> blob dict shaped like the reference loaders expect."""
    if not isinstance(data, str):
        return data
    with open(data) as fh:
        blob = json.load(fh)
    users = list(blob["users"])
    return {
        "users": users,
        "num_samples": list(blob["num_samples"]),
        "user_data": {
            u: np.asarray(blob["user_data"][u]["x"], dtype=np.float32)
            for u in users},
        "user_data_label": {
            u: np.asarray(blob["user_data_label"][u], dtype=np.int64)
            for u in users},
    }


class Dataset(_RefDataset):
    def __init__(self, data, test_only=False, user_idx=0, **kwargs):
        super().__init__(maybe_load(data), test_only=test_only,
                         user_idx=user_idx, **kwargs)
