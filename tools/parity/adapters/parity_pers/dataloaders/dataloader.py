"""Same blob loading as the LR parity adapter."""
from experiments.parity_lr.dataloaders.dataloader import DataLoader  # noqa: F401
