"""Same blob dataset as the LR parity adapter."""
from experiments.parity_lr.dataloaders.dataset import Dataset  # noqa: F401
