"""Personalization parity adapter (VERDICT r3 item 5).

Subclasses the reference's own LR task class
(``experiments/cv_lr_mnist/model.py:23``) with two additions the
personalization flow needs:

- the constructor loads ``pretrained_model_path`` itself: the reference's
  per-user LOCAL models are built by bare ``make_model``
  (``core/client.py:390`` + ``experiments/__init__.py:19``) which draws a
  fresh torch-RNG init — unreproducible cross-framework; loading the seed
  file here pins both sides' local cold-start to the same weights (our
  side: ``personalization_init: initial``);
- ``inference`` returns the dict-output contract the personalized eval
  requires (``convex_inference`` mixes ``output['probabilities']``,
  ``utils/utils.py:598-603``), mirroring the cv experiment's model
  (``experiments/cv/model.py:288-303``: LOG-softmax under the
  'probabilities' key).
"""
import numpy as np
import torch
from experiments.cv_lr_mnist.model import LR as _LR


class LR(_LR):
    def __init__(self, model_config):
        super().__init__(model_config)
        path = model_config.get("pretrained_model_path")
        if path:
            self.load_state_dict(torch.load(path))

    def inference(self, input):
        features, labels = input["x"], input["y"]
        output = self.net(features)
        logp = torch.nn.LogSoftmax(dim=1)(output)
        acc = torch.mean(
            (torch.argmax(output, dim=1) == labels).float()).item()
        n = features.shape[0]
        return {"output": {"probabilities": logp.detach().numpy(),
                           "predictions": np.arange(n),
                           "labels": labels.numpy()},
                "acc": acc, "batch_size": n}
