"""Parity-harness adapter task: re-exports the REFERENCE BERT task class
unchanged (``experiments/mlm_bert/model.py:39``) so the cross-framework
comparison trains the reference's own torch code against a LOCAL tiny
checkpoint dir (``model_name_or_path``) — which also exercises the
reference's pretrained-loading path end to end."""
from experiments.mlm_bert.model import BERT  # noqa: F401
