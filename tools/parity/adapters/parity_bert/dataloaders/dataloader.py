"""Pre-masked MLM parity dataloader: sequential order, default dict
collation (each key stacked), no collator RNG — see dataset.py."""
from core.dataloader import BaseDataLoader
from experiments.parity_bert.dataloaders.dataset import Dataset


class DataLoader(BaseDataLoader):
    def __init__(self, mode, num_workers=0, **kwargs):
        args = kwargs["args"]
        self.batch_size = args["batch_size"]
        dataset = Dataset(kwargs.get("data"),
                          test_only=(mode != "train"),
                          user_idx=kwargs.get("user_idx", 0))
        self.utt_ids = dataset.user
        super().__init__(dataset, batch_size=self.batch_size,
                         shuffle=False, drop_last=False,
                         num_workers=num_workers)
