"""Pre-masked MLM parity dataset.

The reference mlm_bert pipeline tokenizes text and masks it in the HF
``DataCollatorForLanguageModeling`` with torch RNG — per-epoch re-rolls no
cross-framework run can match.  This dataset instead reads blobs whose
``user_data[u]['x']`` are ALREADY-MASKED token id rows and
``user_data_label[u]`` the MLM labels (-100 at unmasked positions), so the
training stream is bit-deterministic.  Interface mirrors the reference
datasets (``experiments/cv_lr_mnist/dataloaders/dataset.py``): user_idx=-1
enumerates, test_only concatenates all users.
"""
import numpy as np
import torch
from core.dataset import BaseDataset
from parity_blob import maybe_load


class Dataset(BaseDataset):
    def __init__(self, data, test_only=False, user_idx=0, **kwargs):
        # maybe_load flattens user_data[u] to the bare feature array
        # (token ids here, hence the int dtype)
        data = maybe_load(data, x_dtype=np.int64)
        self.test_only = test_only
        self.user_list = data["users"]
        self.num_samples = data["num_samples"]
        self.user_data = data["user_data"]
        self.user_data_label = data["user_data_label"]
        if user_idx == -1 or test_only:
            self.user = self.user_list if user_idx == -1 else "test_only"
            self.x = np.concatenate([np.asarray(self.user_data[u])
                                     for u in self.user_list])
            self.y = np.concatenate([np.asarray(self.user_data_label[u])
                                     for u in self.user_list])
        else:
            self.user = self.user_list[user_idx]
            self.x = np.asarray(self.user_data[self.user])
            self.y = np.asarray(self.user_data_label[self.user])

    def load_data(self, **kwargs):  # BaseDataset abstract contract
        pass

    def __len__(self):
        return len(self.x)

    def __getitem__(self, idx):
        ids = torch.as_tensor(self.x[idx], dtype=torch.long)
        return {"input_ids": ids,
                "attention_mask": torch.ones_like(ids),
                "labels": torch.as_tensor(self.y[idx], dtype=torch.long)}
