"""Parity adapter dataset: the reference fed_shakespeare Dataset expects an
already-loaded blob dict (its path mode pulls the LEAF release, impossible
with zero egress) — this subclass adds json-path loading, everything else
is the reference class
(``experiments/nlp_rnn_fedshakespeare/dataloaders/dataset.py``)."""
import functools

import numpy as np

from experiments.nlp_rnn_fedshakespeare.dataloaders.dataset import \
    Dataset as _RefDataset
from parity_blob import maybe_load as _maybe_load

# int [n, L] input sequences + int [n, L] per-position target sequences
maybe_load = functools.partial(_maybe_load, x_dtype=np.int64)


class Dataset(_RefDataset):
    def __init__(self, data, test_only=False, user_idx=0, **kwargs):
        super().__init__(maybe_load(data), test_only=test_only,
                         user_idx=user_idx, **kwargs)
