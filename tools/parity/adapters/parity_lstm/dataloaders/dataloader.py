"""Parity adapter dataloader: the reference fed_shakespeare DataLoader
with json-path loading injected (see dataset.py.maybe_load)."""
from experiments.nlp_rnn_fedshakespeare.dataloaders import dataloader as _ref
from experiments.parity_lstm.dataloaders import dataset as _ds

# the reference DataLoader constructs its Dataset from this module global;
# point it at the path-aware subclass instead
_ref.Dataset = _ds.Dataset


class DataLoader(_ref.DataLoader):
    def __init__(self, mode, num_workers=0, **kwargs):
        kwargs["data"] = _ds.maybe_load(kwargs.get("data"))
        super().__init__(mode, num_workers=num_workers, **kwargs)
