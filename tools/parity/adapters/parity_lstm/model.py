"""Parity-harness adapter task: re-exports the REFERENCE Shakespeare RNN
model class unchanged (``experiments/nlp_rnn_fedshakespeare/model.py:40``)
so the cross-framework comparison trains the reference's own torch code,
not a copy."""
from experiments.nlp_rnn_fedshakespeare.model import RNN  # noqa: F401
