"""Long-horizon cross-framework accuracy run (VERDICT r4, next #5).

The committed parity families are trajectory-exact but SHORT (20-101
rounds, full participation).  The reference's published accuracies live
at protocol scale: 1500 sampled rounds over 3400 FEMNIST users.  Nothing
short-horizon can show the two frameworks agreeing THERE — client
sampling RNG differs by design, so pointwise equality is impossible and
the right comparison is statistical: identical full-size corpus,
identical initial weights, identical hyperparameters, hundreds of
sampled rounds, overlaid val-accuracy curves, endpoint tolerance.

Protocol (reference README.md:22-27 FEMNIST row, CNN benchmark model):

    corpus   3400 users x ~100 samples (uneven 80..120), 28x28, 62 classes
    rounds   300+ (``--rounds``), K=10 sampled/round, batch 20, SGD lr 0.1
    eval     val blob 100 users x 60 samples, every 25 rounds, both sides

Both frameworks consume the SAME hdf5 blobs (json would be GBs of text):
``users / num_samples / user_data/<u>/{x,y}`` — our loader reads it
natively, the reference through ``parity_blob.maybe_load``'s hdf5 branch
(images pre-transposed in its copy, matching its Dataset's ``.T``).

Output: ``PARITY_LONGRUN.json`` — both curves, endpoints, wall-clocks,
and pass/fail on: both-learned (final >= 4x chance), endpoint
``|acc_ref - acc_tpu| <= tol`` (default 0.05), and mean |curve gap| over
the second half <= tol (the first half is steep descent where sampling
noise dominates).

Usage::

    python tools/parity/longrun.py [--rounds 300] [--users 3400]
        [--scratch /tmp/parity_longrun] [--smoke]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))))

import yaml  # noqa: E402

from run_parity import (  # noqa: E402
    REPO, build_ref_tree, cnn_init, gen_blob, ref_config, run_msrflute,
    run_reference, save_flax_cnn, save_torch_cnn, tpu_config,
)


def write_yaml(payload, path):
    with open(path, "w") as fh:
        yaml.safe_dump(payload, fh)


def write_blob_hdf5(blob, path, transpose_images=False):
    import h5py
    with h5py.File(path, "w") as fh:
        grp = fh.create_group("user_data")
        for u in blob["users"]:
            x = np.asarray(blob["user_data"][u]["x"], np.float32)
            if transpose_images and x.ndim == 3:
                x = np.swapaxes(x, 1, 2)
            g = grp.create_group(u)
            g.create_dataset("x", data=x)
            g.create_dataset(
                "y", data=np.asarray(blob["user_data_label"][u], np.int64))
        fh.create_dataset(
            "users", data=np.asarray(blob["users"],
                                     dtype=h5py.string_dtype()))
        fh.create_dataset("num_samples",
                          data=np.asarray(blob["num_samples"]))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=300)
    ap.add_argument("--users", type=int, default=3400)
    ap.add_argument("--clients-per-round", type=int, default=10)
    ap.add_argument("--val-freq", type=int, default=25)
    ap.add_argument("--tol", type=float, default=0.05)
    ap.add_argument("--scratch", default="/tmp/parity_longrun")
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "PARITY_LONGRUN.json"))
    ap.add_argument("--smoke", action="store_true",
                    help="tiny geometry: contract test, minutes not hours")
    args = ap.parse_args()
    if args.smoke:
        args.rounds, args.users, args.val_freq = 6, 24, 2

    scratch = args.scratch
    os.makedirs(scratch, exist_ok=True)
    data_dir = os.path.join(scratch, "data")
    os.makedirs(data_dir, exist_ok=True)
    rng = np.random.default_rng(7)

    # ---- corpus (FEMNIST geometry; uneven sizes keep the aggregation
    # weights load-bearing) ----
    classes, shape = 62, (28, 28)
    sizes = rng.integers(80, 121, size=args.users).tolist() \
        if not args.smoke else [12] * args.users
    means = rng.normal(size=(classes,) + shape).astype(np.float32)
    print(f"[longrun] generating corpus: {args.users} users", file=sys.stderr)
    train = gen_blob(rng, args.users, sizes, shape, classes, sep=1.5,
                     means=means)
    val = gen_blob(rng, 100 if not args.smoke else 8,
                   60 if not args.smoke else 10, shape, classes, sep=1.5,
                   means=means)
    write_blob_hdf5(train, os.path.join(data_dir, "train_ref.hdf5"),
                    transpose_images=True)
    write_blob_hdf5(val, os.path.join(data_dir, "val_ref.hdf5"),
                    transpose_images=True)
    write_blob_hdf5(train, os.path.join(data_dir, "train_tpu.hdf5"))
    write_blob_hdf5(val, os.path.join(data_dir, "val_tpu.hdf5"))

    # ---- identical initial weights ----
    init = cnn_init(np.random.default_rng(11), classes=classes)
    torch_init = os.path.join(scratch, "init_cnn.pt")
    flax_init = os.path.join(scratch, "init_cnn.msgpack")
    save_torch_cnn(init, torch_init)
    save_flax_cnn(init, flax_init)

    # ---- configs: the 20-round parity cnn configs with protocol-scale
    # overrides (sampled K, published cadence) ----
    rcfg = ref_config("cnn", args.rounds, args.users, 20, 0.1, torch_init,
                      classes)
    tcfg = tpu_config("cnn", args.rounds, args.users, 20, 0.1, flax_init,
                      classes)
    for cfg, suffix in ((rcfg, "ref"), (tcfg, "tpu")):
        sc = cfg["server_config"]
        sc["num_clients_per_iteration"] = args.clients_per_round
        sc["val_freq"] = args.val_freq
        sc["data_config"]["val"]["val_data"] = f"val_{suffix}.hdf5"
        sc["data_config"]["test"]["test_data"] = f"val_{suffix}.hdf5"
        cfg["client_config"]["data_config"]["train"][
            "list_of_train_data"] = f"train_{suffix}.hdf5"

    # ---- reference run (its real 2-process gloo mode) ----
    tree = build_ref_tree(scratch)
    ref_cfg_path = os.path.join(scratch, "ref_cnn_longrun.yaml")
    write_yaml(rcfg, ref_cfg_path)
    print(f"[longrun] reference: {args.rounds} rounds", file=sys.stderr)
    tic = time.time()
    ref_rounds = run_reference(
        tree, ref_cfg_path, data_dir, os.path.join(scratch, "ref_out"),
        "parity_cnn", os.path.join(scratch, "ref_metrics.jsonl"))
    ref_secs = time.time() - tic
    # run_reference aligns val records by ORDER (j-th record = round j),
    # which assumes the parity harness's val_freq=1; at cadence F the
    # j-th record is the state after j*F rounds (initial_val record = 0)
    ref_rounds = {r * args.val_freq: v for r, v in ref_rounds.items()}

    # ---- our run ----
    tpu_cfg_path = os.path.join(scratch, "tpu_cnn_longrun.yaml")
    write_yaml(tcfg, tpu_cfg_path)
    print(f"[longrun] msrflute_tpu: {args.rounds} rounds", file=sys.stderr)
    tic = time.time()
    tpu_rounds = run_msrflute(
        tpu_cfg_path, data_dir, os.path.join(scratch, "tpu_out"),
        # a label with no experiments/<name>/task.py: the run must not
        # pick up a plugin's config overrides
        "parity_cnn_longrun",
        # conv-heavy on a small host: 2 virtual devices, single-thread
        # eigen (run_msrflute docstring)
        env_override={
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2 "
                         "--xla_cpu_multi_thread_eigen=false"})
    tpu_secs = time.time() - tic

    # ---- compare ----
    def curve(rounds):
        return sorted((r, v["Val acc"]) for r, v in rounds.items()
                      if "Val acc" in v)

    ref_curve, tpu_curve = curve(ref_rounds), curve(tpu_rounds)
    chance = 1.0 / classes
    ref_final = ref_curve[-1][1] if ref_curve else float("nan")
    tpu_final = tpu_curve[-1][1] if tpu_curve else float("nan")
    shared = sorted(set(r for r, _ in ref_curve) &
                    set(r for r, _ in tpu_curve))
    second_half = [r for r in shared if r >= args.rounds // 2]
    gaps = [abs(dict(ref_curve)[r] - dict(tpu_curve)[r])
            for r in second_half]
    if args.smoke:
        # the smoke run proves the MECHANICS (both stacks ran, curves
        # parsed and aligned); 6 rounds cannot clear learning bars
        checks = {
            "ref_curve_nonempty": bool(ref_curve),
            "tpu_curve_nonempty": bool(tpu_curve),
            "curves_aligned": bool(second_half),
            # no endpoint bar in smoke: at a handful of rounds on a toy
            # corpus the two frameworks' independent client-sampling RNGs
            # dominate the signal
        }
    else:
        checks = {
            "ref_learned": bool(ref_final >= 4 * chance),
            "tpu_learned": bool(tpu_final >= 4 * chance),
            "endpoint_within_tol": bool(
                abs(ref_final - tpu_final) <= args.tol),
            "second_half_mean_gap_within_tol": bool(
                gaps and float(np.mean(gaps)) <= args.tol),
        }
    payload = {
        "kind": "parity_longrun",
        "protocol": {
            "users": args.users, "rounds": args.rounds,
            "clients_per_round": args.clients_per_round,
            "batch": 20, "lr": 0.1, "val_freq": args.val_freq,
            "classes": classes, "smoke": args.smoke,
            "geometry_source": "reference README.md:22-27 FEMNIST row",
        },
        "ref": {"final_val_acc": round(ref_final, 4),
                "wall_secs": round(ref_secs, 1), "curve": ref_curve},
        "tpu": {"final_val_acc": round(tpu_final, 4),
                "wall_secs": round(tpu_secs, 1), "curve": tpu_curve},
        "endpoint_abs_gap": round(abs(ref_final - tpu_final), 4),
        "second_half_mean_gap": (round(float(np.mean(gaps)), 4)
                                 if gaps else None),
        "tol": args.tol,
        "checks": checks,
        "ok": all(checks.values()),
        "captured_at": time.strftime("%Y%m%d_%H%M%S"),
    }
    with open(args.out, "w") as fh:
        json.dump(payload, fh, indent=1)
    print(json.dumps({k: payload[k] for k in
                      ("endpoint_abs_gap", "second_half_mean_gap", "ok")}))
    print(f"[longrun] wrote {args.out}", file=sys.stderr)
    if not payload["ok"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
