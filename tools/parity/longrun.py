"""Long-horizon cross-framework accuracy run (VERDICT r4, next #5).

The committed parity families are trajectory-exact but SHORT (20-101
rounds, full participation).  The reference's published accuracies live
at protocol scale: 1500 sampled rounds over 3400 FEMNIST users.  Nothing
short-horizon can show the two frameworks agreeing THERE — client
sampling RNG differs by design, so pointwise equality is impossible and
the right comparison is statistical: identical full-size corpus,
identical initial weights, identical hyperparameters, hundreds of
sampled rounds, overlaid val-accuracy curves, endpoint tolerance.

Protocol (reference README.md:22-27 FEMNIST row, CNN benchmark model):

    corpus   3400 users x ~100 samples (uneven 80..120), 28x28, 62 classes
    rounds   300+ (``--rounds``), K=10 sampled/round, batch 20, SGD lr 0.1
    eval     val blob 100 users x 60 samples, every 25 rounds, both sides

Both frameworks consume the SAME hdf5 blobs (json would be GBs of text):
``users / num_samples / user_data/<u>/{x,y}`` — our loader reads it
natively, the reference through ``parity_blob.maybe_load``'s hdf5 branch
(images pre-transposed in its copy, matching its Dataset's ``.T``).

Output: ``PARITY_LONGRUN.json`` — both curves, endpoints, wall-clocks,
and pass/fail on: both-learned (final >= 4x chance), endpoint
``|acc_ref - acc_tpu| <= tol`` (default 0.05), and mean |curve gap| over
the second half <= tol (the first half is steep descent where sampling
noise dominates).

Phases (``--phase``): the reference side is a ~25-minute torch-CPU run;
ours is minutes ON CHIP but hours on this 1-core host's XLA-CPU convs —
so each side runs where it is viable and the comparison merges the saved
curves:

- ``ref``      generate the corpus + run the reference (SKIPPED when its
               metrics already exist in the scratch); saves
               ``ref_rounds.json``.
- ``tpu``      run our side; ``--backend ambient`` keeps the caller's
               backend (the TPU queue-job path — ``cpu`` forces the
               virtual-mesh env).  Saves ``tpu_rounds.json``.
- ``compare``  merge the saved curves into ``PARITY_LONGRUN.json``.
- ``all``      every phase in-process (the smoke/CI path).

Usage::

    python tools/parity/longrun.py [--rounds 300] [--users 3400]
        [--scratch /tmp/parity_longrun] [--smoke] [--phase all]
        [--backend cpu|ambient]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))))

import yaml  # noqa: E402

from run_parity import (  # noqa: E402
    REPO, build_ref_tree, cnn_init, parse_ref_val_metrics, ref_config,
    run_msrflute, run_reference, save_flax_cnn, save_torch_cnn, tpu_config,
)

CLASSES, SHAPE = 62, (28, 28)


def write_yaml(payload, path):
    with open(path, "w") as fh:
        yaml.safe_dump(payload, fh)


def write_blob_hdf5(blob, path, transpose_images=False):
    import h5py
    with h5py.File(path, "w") as fh:
        grp = fh.create_group("user_data")
        for u in blob["users"]:
            x = np.asarray(blob["user_data"][u]["x"], np.float32)
            if transpose_images and x.ndim == 3:
                x = np.swapaxes(x, 1, 2)
            g = grp.create_group(u)
            g.create_dataset("x", data=x)
            g.create_dataset(
                "y", data=np.asarray(blob["user_data_label"][u], np.int64))
        fh.create_dataset(
            "users", data=np.asarray(blob["users"],
                                     dtype=h5py.string_dtype()))
        fh.create_dataset("num_samples",
                          data=np.asarray(blob["num_samples"]))


#: corpus difficulty, probed offline with a ridge one-vs-rest ceiling:
#: class separation 0.24 + unit per-user style offsets lands the linear
#: ceiling at ~0.86 on UNSEEN users — FEMNIST-like (~83% published), so
#: the 300-round curve is a real learning curve, not an instant saturate
#: (sep 1.5 without styles measured ceiling 1.0 by round 25).
SEP, STYLE = 0.24, 1.0


def gen_style_blob(rng, users, sizes, means, classes):
    """Class template + PER-USER style offset + unit noise: the writer-
    style structure that keeps held-out-user accuracy below 1.0 (val
    users are unseen writers with their own styles, like FEMNIST's
    held-out-writer split)."""
    per_user = list(sizes) if isinstance(sizes, (list, tuple)) \
        else [sizes] * users
    out = {"users": [], "num_samples": [], "user_data": {},
           "user_data_label": {}}
    for u in range(users):
        n = per_user[u]
        style = (rng.normal(size=means.shape[1:]) * STYLE).astype(
            np.float32)
        y = rng.integers(0, classes, size=(n,))
        x = (SEP * means[y] + style[None]
             + rng.normal(size=(n,) + means.shape[1:])).astype(np.float32)
        name = f"{u:04d}"
        out["users"].append(name)
        out["num_samples"].append(n)
        out["user_data"][name] = {"x": x}
        out["user_data_label"][name] = y.astype(np.int64)
    return out


def prepare(args):
    """Corpus + identical init + both configs.  Idempotent: existing
    blobs are reused (the rng is seed-deterministic, so a re-run would
    write byte-identical data — skipping just saves the GB rewrite)."""
    scratch = args.scratch
    os.makedirs(scratch, exist_ok=True)
    data_dir = os.path.join(scratch, "data")
    os.makedirs(data_dir, exist_ok=True)
    blob_paths = {name: os.path.join(data_dir, name)
                  for name in ("train_ref.hdf5", "val_ref.hdf5",
                               "train_tpu.hdf5", "val_tpu.hdf5")}
    # reuse is keyed on a sidecar of the EXACT corpus parameters, not on
    # file existence: a scratch holding blobs from another geometry, a
    # --smoke run, or an older generator must regenerate — and anything
    # derived from the old corpus (ref metrics, saved curves) is stale
    # with it
    meta = {"generator": "style_blob_v1", "users": args.users,
            "smoke": bool(args.smoke), "sep": SEP, "style": STYLE}
    meta_path = os.path.join(data_dir, "corpus_meta.json")
    have_meta = None
    if os.path.exists(meta_path):
        try:
            with open(meta_path) as fh:
                have_meta = json.load(fh)
        except Exception:
            have_meta = None
    if have_meta != meta or \
            not all(os.path.exists(p) for p in blob_paths.values()):
        for stale in ("ref_metrics.jsonl", "ref_rounds.json",
                      "tpu_rounds.json"):
            stale_path = os.path.join(scratch, stale)
            if os.path.exists(stale_path):
                os.remove(stale_path)
        rng = np.random.default_rng(7)
        sizes = rng.integers(80, 121, size=args.users).tolist() \
            if not args.smoke else [12] * args.users
        means = rng.normal(size=(CLASSES,) + SHAPE).astype(np.float32)
        print(f"[longrun] generating corpus: {args.users} users",
              file=sys.stderr)
        train = gen_style_blob(rng, args.users, sizes, means, CLASSES)
        val = gen_style_blob(rng, 100 if not args.smoke else 8,
                             60 if not args.smoke else 10, means, CLASSES)
        write_blob_hdf5(train, blob_paths["train_ref.hdf5"],
                        transpose_images=True)
        write_blob_hdf5(val, blob_paths["val_ref.hdf5"],
                        transpose_images=True)
        write_blob_hdf5(train, blob_paths["train_tpu.hdf5"])
        write_blob_hdf5(val, blob_paths["val_tpu.hdf5"])
        with open(meta_path, "w") as fh:
            json.dump(meta, fh)

    # identical initial weights
    init = cnn_init(np.random.default_rng(11), classes=CLASSES)
    torch_init = os.path.join(scratch, "init_cnn.pt")
    flax_init = os.path.join(scratch, "init_cnn.msgpack")
    if not os.path.exists(torch_init):
        save_torch_cnn(init, torch_init)
    if not os.path.exists(flax_init):
        save_flax_cnn(init, flax_init)

    # the 20-round parity cnn configs with protocol-scale overrides
    # (sampled K, published cadence)
    rcfg = ref_config("cnn", args.rounds, args.users, 20, 0.1, torch_init,
                      CLASSES)
    tcfg = tpu_config("cnn", args.rounds, args.users, 20, 0.1, flax_init,
                      CLASSES)
    for cfg, suffix in ((rcfg, "ref"), (tcfg, "tpu")):
        sc = cfg["server_config"]
        sc["num_clients_per_iteration"] = args.clients_per_round
        sc["val_freq"] = args.val_freq
        sc["data_config"]["val"]["val_data"] = f"val_{suffix}.hdf5"
        sc["data_config"]["test"]["test_data"] = f"val_{suffix}.hdf5"
        cfg["client_config"]["data_config"]["train"][
            "list_of_train_data"] = f"train_{suffix}.hdf5"
    return data_dir, rcfg, tcfg


def _protocol(args):
    """The run parameters a saved curve was produced with — persisted
    beside the curve so ``compare`` judges what actually ran, not what
    the compare invocation's flags happen to say."""
    return {"users": args.users, "rounds": args.rounds,
            "clients_per_round": args.clients_per_round,
            "batch": 20, "lr": 0.1, "val_freq": args.val_freq,
            "smoke": bool(args.smoke)}


def _save_rounds(path, rounds, wall_secs, protocol):
    with open(path, "w") as fh:
        json.dump({"rounds": {str(r): v for r, v in rounds.items()},
                   "wall_secs": wall_secs, "protocol": protocol}, fh)


def _load_rounds(path):
    with open(path) as fh:
        d = json.load(fh)
    return ({int(r): v for r, v in d["rounds"].items()},
            d.get("wall_secs"), d.get("protocol"))


def phase_ref(args, data_dir, rcfg):
    metrics_path = os.path.join(args.scratch, "ref_metrics.jsonl")
    proto_path = os.path.join(args.scratch, "ref_metrics_protocol.json")
    out_path = os.path.join(args.scratch, "ref_rounds.json")
    expected_evals = args.rounds // args.val_freq + 1  # + initial_val
    if os.path.exists(metrics_path) and os.path.getsize(metrics_path):
        # reuse ONLY a complete capture FROM THIS PROTOCOL: the metrics
        # are written incrementally (a crashed run leaves a truncated
        # curve), and an eval-point count alone cannot tell 300/25 from
        # 120/10 — the protocol sidecar written alongside a successful
        # run is the authority
        have_proto = None
        if os.path.exists(proto_path):
            try:
                with open(proto_path) as fh:
                    have_proto = json.load(fh)
            except Exception:
                have_proto = None
        parsed = parse_ref_val_metrics(metrics_path)
        if have_proto == _protocol(args) and len(parsed) == expected_evals:
            print("[longrun] complete reference metrics for this protocol "
                  "already on disk; parsing without re-running",
                  file=sys.stderr)
            _save_rounds(out_path,
                         {j * args.val_freq: v for j, v in parsed.items()},
                         None, _protocol(args))
            return
        print(f"[longrun] on-disk reference metrics unusable (protocol "
              f"match: {have_proto == _protocol(args)}; "
              f"{len(parsed)}/{expected_evals} eval points); re-running",
              file=sys.stderr)
    tree = build_ref_tree(args.scratch)
    ref_cfg_path = os.path.join(args.scratch, "ref_cnn_longrun.yaml")
    write_yaml(rcfg, ref_cfg_path)
    print(f"[longrun] reference: {args.rounds} rounds", file=sys.stderr)
    tic = time.time()
    ref_rounds = run_reference(
        tree, ref_cfg_path, data_dir, os.path.join(args.scratch, "ref_out"),
        "parity_cnn", metrics_path)
    # run_reference's order alignment assumes the parity harness's
    # val_freq=1; at cadence F the j-th record is round j*F
    ref_rounds = {r * args.val_freq: v for r, v in ref_rounds.items()}
    with open(proto_path, "w") as fh:
        json.dump(_protocol(args), fh)  # marks the capture's protocol
    _save_rounds(out_path, ref_rounds, round(time.time() - tic, 1),
                 _protocol(args))


def phase_tpu(args, data_dir, tcfg):
    tpu_cfg_path = os.path.join(args.scratch, "tpu_cnn_longrun.yaml")
    write_yaml(tcfg, tpu_cfg_path)
    if args.backend == "ambient":
        # queue-job path: keep the caller's backend (axon chip under the
        # runner; run_msrflute's base env would force the CPU mesh)
        env_override = {
            "PALLAS_AXON_POOL_IPS":
                os.environ.get("PALLAS_AXON_POOL_IPS", ""),
            "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", ""),
            "XLA_FLAGS": os.environ.get("XLA_FLAGS", ""),
        }
    else:
        # conv-heavy on a small host: 2 virtual devices, single-thread
        # eigen (run_msrflute docstring).  Overridable: on hosts with
        # real cores the single-thread default makes the 300-round CNN
        # protocol ~176 s/round (measured 2026-08-01) — hopeless; let
        # the operator trade SIGABRT risk for throughput explicitly.
        env_override = {
            "XLA_FLAGS": os.environ.get(
                "LONGRUN_CPU_XLA_FLAGS",
                "--xla_force_host_platform_device_count=2 "
                "--xla_cpu_multi_thread_eigen=false")}
    print(f"[longrun] msrflute_tpu: {args.rounds} rounds "
          f"(backend={args.backend})", file=sys.stderr)
    tic = time.time()
    tpu_rounds = run_msrflute(
        tpu_cfg_path, data_dir, os.path.join(args.scratch, "tpu_out"),
        # a label with no experiments/<name>/task.py: the run must not
        # pick up a plugin's config overrides
        "parity_cnn_longrun", env_override=env_override,
        # the budget must kill the TRAINER (the tunnel claimant), not an
        # outer orchestrator — queue jobs therefore pass it HERE instead
        # of wrapping this tool in a shell `timeout`
        timeout=args.tpu_timeout_secs)
    _save_rounds(os.path.join(args.scratch, "tpu_rounds.json"),
                 tpu_rounds, round(time.time() - tic, 1), _protocol(args))


def phase_compare(args):
    ref_rounds, ref_secs, ref_proto = _load_rounds(
        os.path.join(args.scratch, "ref_rounds.json"))
    tpu_rounds, tpu_secs, tpu_proto = _load_rounds(
        os.path.join(args.scratch, "tpu_rounds.json"))
    # judge what RAN: the persisted protocols are authoritative over the
    # compare invocation's flags — and the two sides must agree with
    # each other before their curves are comparable at all
    if ref_proto and tpu_proto and ref_proto != tpu_proto:
        raise SystemExit(
            f"[longrun] ref and tpu curves were produced under different "
            f"protocols — not comparable:\n  ref: {ref_proto}\n  "
            f"tpu: {tpu_proto}")
    proto = ref_proto or tpu_proto or _protocol(args)
    rounds_ran = int(proto["rounds"])
    smoke = bool(proto["smoke"])

    def curve(rounds):
        return sorted((r, v["Val acc"]) for r, v in rounds.items()
                      if "Val acc" in v)

    ref_curve, tpu_curve = curve(ref_rounds), curve(tpu_rounds)
    chance = 1.0 / CLASSES
    ref_final = ref_curve[-1][1] if ref_curve else float("nan")
    tpu_final = tpu_curve[-1][1] if tpu_curve else float("nan")
    shared = sorted(set(r for r, _ in ref_curve) &
                    set(r for r, _ in tpu_curve))
    second_half = [r for r in shared if r >= rounds_ran // 2]
    gaps = [abs(dict(ref_curve)[r] - dict(tpu_curve)[r])
            for r in second_half]
    if smoke:
        # the smoke run proves the MECHANICS (both stacks ran, curves
        # parsed and aligned); 6 rounds cannot clear learning bars
        checks = {
            "ref_curve_nonempty": bool(ref_curve),
            "tpu_curve_nonempty": bool(tpu_curve),
            "curves_aligned": bool(second_half),
            # no endpoint bar in smoke: at a handful of rounds on a toy
            # corpus the two frameworks' independent client-sampling RNGs
            # dominate the signal
        }
    else:
        checks = {
            "ref_learned": bool(ref_final >= 4 * chance),
            "tpu_learned": bool(tpu_final >= 4 * chance),
            "endpoint_within_tol": bool(
                abs(ref_final - tpu_final) <= args.tol),
            "second_half_mean_gap_within_tol": bool(
                gaps and float(np.mean(gaps)) <= args.tol),
        }
    payload = {
        "kind": "parity_longrun",
        "protocol": {
            **proto, "classes": CLASSES,
            "corpus": f"style_blob_v1 sep={SEP} style={STYLE}",
            "geometry_source": "reference README.md:22-27 FEMNIST row",
        },
        "ref": {"final_val_acc": round(ref_final, 4),
                "wall_secs": ref_secs, "curve": ref_curve},
        "tpu": {"final_val_acc": round(tpu_final, 4),
                "wall_secs": tpu_secs, "curve": tpu_curve},
        "endpoint_abs_gap": round(abs(ref_final - tpu_final), 4),
        "second_half_mean_gap": (round(float(np.mean(gaps)), 4)
                                 if gaps else None),
        "tol": args.tol,
        "checks": checks,
        "ok": all(checks.values()),
        "captured_at": time.strftime("%Y%m%d_%H%M%S"),
    }
    with open(args.out, "w") as fh:
        json.dump(payload, fh, indent=1)
    print(json.dumps({k: payload[k] for k in
                      ("endpoint_abs_gap", "second_half_mean_gap", "ok")}))
    print(f"[longrun] wrote {args.out}", file=sys.stderr)
    if not payload["ok"]:
        sys.exit(1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=300)
    ap.add_argument("--users", type=int, default=3400)
    ap.add_argument("--clients-per-round", type=int, default=10)
    ap.add_argument("--val-freq", type=int, default=25)
    ap.add_argument("--tol", type=float, default=0.05)
    ap.add_argument("--scratch", default="/tmp/parity_longrun")
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "PARITY_LONGRUN.json"))
    ap.add_argument("--smoke", action="store_true",
                    help="tiny geometry: contract test, minutes not hours")
    ap.add_argument("--phase", default="all",
                    choices=["all", "ref", "tpu", "compare"])
    ap.add_argument("--backend", default="cpu",
                    choices=["cpu", "ambient"],
                    help="tpu phase: cpu = virtual-mesh env (smoke/CI); "
                         "ambient = keep the caller's backend (chip jobs)")
    ap.add_argument("--tpu-timeout-secs", type=float, default=None,
                    help="kill the tpu-phase TRAINER after this budget "
                         "(the trainer holds the tunnel claim; an outer "
                         "shell timeout would orphan it)")
    args = ap.parse_args()
    if args.smoke:
        args.rounds, args.users, args.val_freq = 6, 24, 2

    if args.phase == "compare":
        # compare reads only the saved curves; running prepare() here
        # could regenerate the GB corpus for nothing — or, on a flag
        # mismatch, DELETE the very curves it is about to compare
        phase_compare(args)
        return
    data_dir, rcfg, tcfg = prepare(args)
    if args.phase in ("all", "ref"):
        phase_ref(args, data_dir, rcfg)
    if args.phase in ("all", "tpu"):
        phase_tpu(args, data_dir, tcfg)
    if args.phase == "all":
        phase_compare(args)


if __name__ == "__main__":
    main()
