"""Offline DP accounting CLI — PRV (near-exact) and RDP (upper bound).

Role parity: the reference's ``dp-accountant`` submodule ships
``compute-dp-epsilon -p SAMPLING_PROBABILITY -s NOISE_MULTIPLIER
-i ITERATIONS -d DELTA`` (reference ``README.md:162-171``); accounting is
done offline from the parameters the server logs (``README.md:160``,
mirrored by our ``update_privacy_accountant`` metrics records).

Usage::

    python tools/compute_dp_epsilon.py -p 0.01 -s 1.0 -i 1000 -d 1e-6

Prints one JSON line with the PRV bracket (eps_lower/estimate/upper) and
the RDP upper bound for cross-checking.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-p", "--sampling-probability", type=float, required=True)
    ap.add_argument("-s", "--noise-multiplier", type=float, required=True)
    ap.add_argument("-i", "--iterations", type=int, required=True)
    ap.add_argument("-d", "--delta", type=float, required=True)
    ap.add_argument("--eps-error", type=float, default=0.1,
                    help="PRV discretization budget (default 0.1)")
    args = ap.parse_args(argv)

    from msrflute_tpu.privacy.accountant import (DEFAULT_ORDERS, compute_rdp,
                                                 get_privacy_spent)
    from msrflute_tpu.privacy.prv import compute_dp_epsilon

    out = compute_dp_epsilon(args.sampling_probability,
                             args.noise_multiplier, args.iterations,
                             args.delta, eps_error=args.eps_error)
    rdp = compute_rdp(args.sampling_probability, args.noise_multiplier,
                      args.iterations, DEFAULT_ORDERS)
    rdp_eps, opt_order = get_privacy_spent(DEFAULT_ORDERS, rdp, args.delta)
    out["rdp_eps_upper"] = rdp_eps
    out["rdp_opt_order"] = opt_order
    print(json.dumps({k: (round(v, 6) if isinstance(v, float) else v)
                      for k, v in out.items()}))


if __name__ == "__main__":
    main()
