"""Turn a committed flash_crossover.json sweep into concrete settings.

``tools/flash_crossover_sweep.py`` (queue job 92) measures fwd+bwd wall
time of dense vs flash per length x kernel-tile choice.  This tool reads
that artifact and prints, per length: the best tile, the flash/dense
speedup, and the recommended settings —

- ``FLASH_AUTO_MIN_LEN`` (``models/ringlm.py``): the smallest measured
  length where the best flash beats dense (the constant stays STATIC in
  code by design; this tool makes the manual re-derivation mechanical
  and reviewable).
- ``flash_block_q`` / ``flash_block_k`` (model_config): the tile pair
  winning at the lengths where flash is the chosen path.

Usage::

    python tools/calibrate_flash.py [flash_crossover.json]
"""

from __future__ import annotations

import json
import os
import sys


def analyze(path: str) -> dict:
    with open(path) as fh:
        res = json.load(fh)
    lengths = {}
    for ls, row in sorted(res.get("lengths", {}).items(),
                          key=lambda kv: int(kv[0])):
        best_tile, best_ms = None, None
        for key, val in row.items():
            if key.startswith("flash_") and key.endswith("_fwd_bwd_ms") \
                    and isinstance(val, (int, float)):
                if best_ms is None or val < best_ms:
                    best_ms, best_tile = val, key[len("flash_"):
                                                  -len("_fwd_bwd_ms")]
        dense = row.get("dense_fwd_bwd_ms")
        lengths[int(ls)] = {
            "best_tile": best_tile,
            "best_flash_ms": best_ms,
            "dense_ms": dense,
            "flash_speedup": (round(dense / best_ms, 3)
                              if dense and best_ms else None),
        }
    crossover = None
    for L in sorted(lengths):
        row = lengths[L]
        if row["best_flash_ms"] is None and row["dense_ms"] is None:
            # no data at this length (both paths failed/skipped): it can
            # neither establish nor refute a crossover — leave the scan
            # state untouched instead of counting it as a flash loss
            continue
        wins = (row["flash_speedup"] or 0) > 1.0 or \
            (row["best_flash_ms"] is not None and row["dense_ms"] is None)
        if wins and crossover is None:
            crossover = L
        if not wins:
            crossover = None  # must win at every length >= the crossover
    win_tiles = [lengths[L]["best_tile"] for L in sorted(lengths)
                 if crossover is not None and L >= crossover and
                 lengths[L]["best_tile"]]
    return {
        "lengths": lengths,
        "recommended_flash_auto_min_len": crossover,
        "recommended_tiles_at_win_lengths": win_tiles,
    }


def main() -> None:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = sys.argv[1] if len(sys.argv) > 1 else \
        os.path.join(repo, "flash_crossover.json")
    out = analyze(path)
    print(json.dumps(out, indent=1))
    rec = out["recommended_flash_auto_min_len"]
    if rec is None:
        print("\n[calibrate] flash never beats dense in this sweep — "
              "FLASH_AUTO_MIN_LEN should stay above the largest measured "
              "length; kernel work needed", file=sys.stderr)
    else:
        print(f"\n[calibrate] set FLASH_AUTO_MIN_LEN = {rec} "
              f"(models/ringlm.py); winning tiles per length: "
              f"{out['recommended_tiles_at_win_lengths']}", file=sys.stderr)


if __name__ == "__main__":
    main()
