"""Crash-point fuzzer (flutearmor leg 3): kill the run at EVERY durable
commit point and prove it always resumes bit-identical.

A training run's durable state advances through a small set of atomic
commits — ``os.replace``/``os.rename``/``os.link`` under the model dir:
the two-slot ``latest`` rotation, the orbax pointer, ``status_log.json``,
the checksum sidecars, the fleet row-store ``.npz`` spills and their
round marker.  The recovery contract says a process death at ANY point
in any of those sequences leaves the tree loadable, and a relaunch
trains on to final params bit-identical to an uninterrupted run (a hard
kill may roll back to the previous durable anchor and re-train forward;
the round-keyed RNG anchors make the replay exact).

This tool makes that claim exhaustive instead of anecdotal: it first
runs a CENSUS pass that counts every durable op a run performs, then for
each op index k re-runs from scratch, raises :class:`CrashPoint` (a
``BaseException``, so no retry ladder or best-effort ``except
Exception`` can swallow it) immediately BEFORE op k — simulating death
with the commit un-landed — relaunches with
``resume_from_checkpoint: true``, and asserts the finished params equal
the uninterrupted baseline bit for bit.  ``--phase post`` kills right
AFTER each commit instead (death with the commit landed but every
in-memory postcondition lost).  Both serial and depth-3 pipelined loops
are fuzzed; checkpointing is forced synchronous so every durable op
happens on the training thread (the async writer's op ordering is
documented as not resume-reproducible).

Run: ``python tools/crashpoint.py`` (CPU, ~minutes for the full
matrix); ``tests/test_crashpoint.py`` drives :func:`fuzz` on a small
point subset inside tier-1's budget.  Exit 0 iff every kill point
resumed bit-identical.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

#: the atomic-commit syscalls a durable-write sequence ends with
DURABLE_OPS = ("replace", "rename", "link")


class CrashPoint(BaseException):
    """Simulated process death at a durable commit point.  Derives from
    ``BaseException`` on purpose: the retry ladder and every best-effort
    ``except Exception`` in the host tail must treat it like SIGKILL,
    not like a transient IO error to absorb."""


class KillSwitch:
    """Intercepts the durable-commit syscalls, scoped to one model dir.

    ``arm(dir, kill_at=None)`` counts ops (census mode); with
    ``kill_at=k`` it raises :class:`CrashPoint` at op k — before the
    commit in phase ``pre``, after it in phase ``post``."""

    def __init__(self) -> None:
        self._orig = {name: getattr(os, name) for name in DURABLE_OPS}
        self.scope_dir: str | None = None
        self.kill_at: int | None = None
        self.phase = "pre"
        self.count = 0
        self.log: list = []

    def install(self) -> None:
        for name in DURABLE_OPS:
            setattr(os, name, self._wrap(name))

    def uninstall(self) -> None:
        for name, orig in self._orig.items():
            setattr(os, name, orig)

    def arm(self, scope_dir: str, kill_at: int | None = None,
            phase: str = "pre") -> None:
        self.scope_dir = os.path.abspath(scope_dir)
        self.kill_at = kill_at
        self.phase = phase
        self.count = 0
        self.log = []

    def disarm(self) -> None:
        self.scope_dir = None
        self.kill_at = None

    def _wrap(self, name):
        orig = self._orig[name]

        def wrapped(src, dst, *args, **kwargs):
            scope = self.scope_dir
            in_scope = (scope is not None and
                        os.path.abspath(str(dst)).startswith(scope))
            if not in_scope:
                return orig(src, dst, *args, **kwargs)
            k = self.count
            self.count += 1
            self.log.append(
                (name, os.path.relpath(os.path.abspath(str(dst)), scope)))
            if self.kill_at == k and self.phase == "pre":
                raise CrashPoint(
                    f"killed BEFORE durable op #{k}: {name} -> {dst}")
            out = orig(src, dst, *args, **kwargs)
            if self.kill_at == k and self.phase == "post":
                raise CrashPoint(
                    f"killed AFTER durable op #{k}: {name} -> {dst}")
            return out
        return wrapped


def _config(depth: int, rounds: int, resume: bool = False):
    from msrflute_tpu.config import FLUTEConfig
    return FLUTEConfig.from_dict({
        "model_config": {"model_type": "LR", "num_classes": 4,
                         "input_dim": 8},
        "strategy": "scaffold",  # fused_carry paged carry: the row-store
        "server_config": {       # spill + marker sequences are in play
            "max_iteration": rounds, "num_clients_per_iteration": 4,
            "initial_lr_client": 0.2, "pipeline_depth": depth,
            "fused_carry": True, "rounds_per_step": 1,
            "val_freq": 10_000, "initial_val": False,
            "optimizer_config": {"type": "sgd", "lr": 1.0},
            "data_config": {},
            # a tiny host cache forces spill-through, so the .npz +
            # marker pairing is part of every fuzzed sequence
            "fleet": {"page_pool_slots": 16, "host_cache_rows": 2,
                      "spill_freq": 1},
            # synchronous checkpoints: every durable op on the training
            # thread, op order deterministic (the fuzz precondition)
            "checkpoint_async": False,
            "checkpoint_retry": {"retries": 2, "backoff_base_s": 0.0,
                                 "jitter": 0.0},
            **({"resume_from_checkpoint": True} if resume else {}),
        },
        "client_config": {
            "optimizer_config": {"type": "sgd", "lr": 0.2},
            "data_config": {"train": {"batch_size": 4}}},
    })


def _dataset():
    sys.path.insert(0, os.path.join(REPO_ROOT, "tests"))
    from conftest import make_synthetic_classification
    return make_synthetic_classification()


def _run(cfg, model_dir: str, dataset):
    import jax
    import numpy as np
    from jax.flatten_util import ravel_pytree

    from msrflute_tpu.engine import OptimizationServer
    from msrflute_tpu.models import make_task

    server = OptimizationServer(make_task(cfg.model_config), cfg, dataset,
                                model_dir=model_dir, seed=7)
    state = server.train()
    return np.asarray(ravel_pytree(jax.device_get(state.params))[0])


def fuzz(depth: int = 0, rounds: int = 3, phase: str = "pre",
         kill_points=None, stride: int = 1, workdir: str | None = None,
         verbose: bool = True) -> dict:
    """Run the kill matrix for one loop mode; returns the record
    (census size, points fuzzed, per-point ops).  AssertionError on the
    first kill point whose resumed run is not bit-identical."""
    import numpy as np

    from msrflute_tpu.utils.backend import force_cpu_backend
    force_cpu_backend()

    import tempfile
    workdir = workdir or tempfile.mkdtemp(prefix="crashpoint_")
    dataset = _dataset()

    baseline = _run(_config(depth, rounds),
                    os.path.join(workdir, f"baseline_d{depth}"), dataset)

    switch = KillSwitch()
    switch.install()
    try:
        # census: how many durable commits does this loop mode perform?
        census_dir = os.path.join(workdir, f"census_d{depth}")
        switch.arm(census_dir)
        _run(_config(depth, rounds), census_dir, dataset)
        n_ops = switch.count
        census = list(switch.log)
        switch.disarm()

        points = sorted(set(kill_points)) if kill_points is not None \
            else list(range(n_ops))
        if stride > 1:
            # always keep the first and last commit; subsample between
            points = sorted(set(points[::stride]) | {points[-1]})
        for k in points:
            assert 0 <= k < n_ops, f"kill point {k} outside census {n_ops}"
            run_dir = os.path.join(workdir, f"d{depth}_{phase}_k{k:03d}")
            switch.arm(run_dir, kill_at=k, phase=phase)
            died = False
            try:
                _run(_config(depth, rounds), run_dir, dataset)
            except CrashPoint as exc:
                died = True
                if verbose:
                    print(f"[crashpoint] d{depth} {phase} k={k}: {exc}")
            finally:
                switch.disarm()
            assert died, f"kill point {k} never fired (census drift?)"
            # the relaunch: resume must find a loadable tree (possibly
            # rolled back one anchor) and re-train to the same bits
            flat = _run(_config(depth, rounds, resume=True), run_dir,
                        dataset)
            assert np.array_equal(baseline, flat), (
                f"kill at durable op {k} ({census[k]}, phase {phase}, "
                f"depth {depth}) resumed to DIFFERENT final params")
    finally:
        switch.uninstall()

    record = {
        "depth": depth, "rounds": rounds, "phase": phase,
        "durable_ops": n_ops, "points_fuzzed": len(points),
        "census": [f"{op}:{rel}" for op, rel in census],
    }
    if verbose:
        print(f"[crashpoint] depth {depth} phase {phase}: "
              f"{len(points)}/{n_ops} kill points resumed bit-identical")
    return record


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--depths", type=int, nargs="*", default=[0, 3],
                    help="loop modes to fuzz (0=serial, 3=depth-3 ring)")
    ap.add_argument("--phase", choices=("pre", "post", "both"),
                    default="pre",
                    help="kill before the commit, after it, or both")
    ap.add_argument("--stride", type=int, default=1,
                    help="fuzz every stride-th kill point (1 = all)")
    ap.add_argument("--report", default=None,
                    help="write the JSON record here")
    args = ap.parse_args(argv)

    phases = ("pre", "post") if args.phase == "both" else (args.phase,)
    records = []
    for depth in args.depths:
        for phase in phases:
            records.append(fuzz(depth=depth, rounds=args.rounds,
                                phase=phase, stride=args.stride))
    out = {"kill_matrix": records}
    if args.report:
        with open(args.report, "w") as fh:
            json.dump(out, fh, indent=2)
    print(json.dumps({r["phase"] + f"_d{r['depth']}":
                      f"{r['points_fuzzed']}/{r['durable_ops']}"
                      for r in records}, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
