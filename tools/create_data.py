"""Synthetic dataset generator for smoke tests.

Parity target: reference ``testing/create_data.py`` — builds tiny dummy
datasets per task (truncated LEAF Reddit for nlg_gru/mlm_bert, CIFAR split
into synthetic users, random ECG) so the e2e trainer can run without real
downloads (``testing/README.md:3``: "evaluate the operation of the tasks,
not the performance").

Usage:
    python tools/create_data.py --task cv_lr_mnist --out ./data [--users 25]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _write(path, blob):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        json.dump(blob, fh)
    print(f"wrote {path}")


def _image_blob(rng, users, lo, hi, shape, classes):
    names = [f"u{i:04d}" for i in range(users)]
    data, labels, counts = {}, {}, []
    for u in names:
        n = int(rng.integers(lo, hi))
        x = rng.normal(size=(n,) + shape).round(3)
        y = rng.integers(0, classes, size=n)
        data[u] = {"x": x.tolist()}
        labels[u] = y.tolist()
        counts.append(n)
    return {"users": names, "num_samples": counts, "user_data": data,
            "user_data_label": labels}


def _text_blob(rng, users, lo, hi, sentence_pool):
    names = [f"u{i:04d}" for i in range(users)]
    data, counts = {}, []
    for u in names:
        n = int(rng.integers(lo, hi))
        data[u] = {"x": [sentence_pool[int(rng.integers(len(sentence_pool)))]
                         for _ in range(n)]}
        counts.append(n)
    return {"users": names, "num_samples": counts, "user_data": data}


WORDS = ("the of and to in a is that it was for on are with as his they at be "
         "this have from or one had by word but not what all were we when "
         "your can said there use an each which she do how their if").split()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--task", required=True)
    ap.add_argument("--out", default="./data")
    ap.add_argument("--users", type=int, default=25)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--alpha", type=float, default=0.5,
                    help="Dirichlet concentration for the non-IID cv task")
    args = ap.parse_args()
    rng = np.random.default_rng(args.seed)
    task, out, users = args.task, args.out, args.users

    if task == "cv_lr_mnist":
        for split, seed in (("train", 0), ("val", 1), ("test", 2)):
            r = np.random.default_rng(seed)
            _write(os.path.join(out, "mnist", f"{split}.json"),
                   _image_blob(r, users, 8, 30, (784,), 10))
    elif task in ("cv_cnn_femnist",):
        for split, seed in (("train", 0), ("val", 1), ("test", 2)):
            r = np.random.default_rng(seed)
            _write(os.path.join(out, "femnist", f"{split}.json"),
                   _image_blob(r, users, 8, 30, (28, 28), 62))
    elif task == "cv_resnet_fedcifar100":
        for split, seed in (("train", 0), ("val", 1), ("test", 2)):
            r = np.random.default_rng(seed)
            _write(os.path.join(out, "fedcifar100", f"{split}.json"),
                   _image_blob(r, users, 4, 12, (32, 32, 3), 100))
    elif task == "nlp_rnn_fedshakespeare":
        lines = ["To be, or not to be: that is the question:",
                 "Whether 'tis nobler in the mind to suffer",
                 "The slings and arrows of outrageous fortune,",
                 "Or to take arms against a sea of troubles."]
        for split, seed in (("train", 0), ("val", 1), ("test", 2)):
            r = np.random.default_rng(seed)
            _write(os.path.join(out, "shakespeare", f"{split}.json"),
                   _text_blob(r, users, 4, 16, lines))
    elif task == "nlg_gru":
        vocab = {w: i + 1 for i, w in enumerate(WORDS)}
        vocab["<unk>"] = 0
        os.makedirs(os.path.join(out, "mockup"), exist_ok=True)
        with open(os.path.join(out, "mockup", "vocab_reddit.vocab"), "w") as fh:
            json.dump(vocab, fh)
        sentences = [" ".join(np.random.default_rng(i).choice(
            WORDS, size=12)) for i in range(40)]
        for split, name, seed in (("train", "train_data", 0),
                                  ("val", "val_data", 1),
                                  ("test", "test_data", 2)):
            r = np.random.default_rng(seed)
            _write(os.path.join(out, "mockup", f"{name}.json"),
                   _text_blob(r, users, 4, 16, sentences))
    elif task == "ringlm":
        # long-context documents: repeated phrase soup per user, as raw
        # text — the char featurizer window-truncates to seq_len
        for split, seed in (("train", 0), ("val", 1), ("test", 2)):
            r = np.random.default_rng(seed)
            docs = [" ".join(r.choice(WORDS, size=200)) for _ in range(16)]
            _write(os.path.join(out, "longtext", f"{split}.json"),
                   _text_blob(r, users, 2, 6, docs))
    elif task == "ecg_cnn":
        for split, seed in (("train", 0), ("val", 1), ("test", 2)):
            r = np.random.default_rng(seed)
            _write(os.path.join(out, "ecg", f"{split}.json"),
                   _image_blob(r, users, 8, 24, (187,), 5))
    elif task == "classif_cnn":
        for split, seed in (("train", 0), ("val", 1), ("test", 2)):
            r = np.random.default_rng(seed)
            _write(os.path.join(out, "cifar", f"{split}.json"),
                   _image_blob(r, users, 8, 24, (32, 32, 3), 10))
    elif task == "cv":
        # personalization cv: Dirichlet label-skew + per-client rotation
        # wedges (reference experiments/cv/data.py DataPartitioner)
        from msrflute_tpu.data.partition import dirichlet_blob
        for split, seed, n_flat, train in (("train", 0, 24 * users, True),
                                           ("val", 1, 8 * users, False),
                                           ("test", 2, 8 * users, False)):
            r = np.random.default_rng(seed)
            x = r.normal(size=(n_flat, 32, 32, 3)).round(3)
            y = r.integers(0, 10, size=n_flat)
            _write(os.path.join(out, "cifar", f"{split}.json"),
                   dirichlet_blob(x, y, users, args.alpha, r,
                                  rotate=True, is_train=train))
    elif task == "semisupervision":
        # labeled x/y + unlabeled ux per user; ux_rand is produced at
        # featurize time by the config's data_config.train.augment
        for split, seed in (("train_semisup", 0), ("val", 1), ("test", 2)):
            r = np.random.default_rng(seed)
            blob = _image_blob(r, users, 8, 24, (32, 32, 3), 10)
            if split == "train_semisup":
                for u, n in zip(blob["users"], blob["num_samples"]):
                    blob["user_data"][u]["ux"] = r.normal(
                        size=(n, 32, 32, 3)).round(3).tolist()
            _write(os.path.join(out, "cifar", f"{split}.json"), blob)
    elif task == "fednewsrec":
        # MIND-style: per-user click histories + impression slates
        title_len, vocab = 12, 500
        def _titles(r, n):
            return [r.integers(1, vocab, size=int(r.integers(4, title_len))
                               ).tolist() for _ in range(n)]
        for split, seed in (("train", 0), ("val", 1), ("test", 2)):
            r = np.random.default_rng(seed)
            names = [f"u{i:04d}" for i in range(users)]
            data, counts = {}, []
            for u in names:
                n_imp = int(r.integers(2, 6))
                imps = []
                for _ in range(n_imp):
                    c = int(r.integers(5, 9))
                    labels = np.zeros(c, int)
                    labels[r.integers(0, c)] = 1
                    imps.append({"cands": _titles(r, c),
                                 "labels": labels.tolist()})
                data[u] = {"clicked": _titles(r, int(r.integers(3, 10))),
                           "impressions": imps}
                counts.append(n_imp)
            _write(os.path.join(out, "mind", f"{split}.json"),
                   {"users": names, "num_samples": counts, "user_data": data})
    elif task == "mlm_bert":
        for split, seed in (("train", 0), ("val", 1), ("test", 2)):
            r = np.random.default_rng(seed)
            names = [f"u{i:04d}" for i in range(users)]
            data, counts = {}, []
            for u in names:
                n = int(r.integers(4, 12))
                data[u] = {"x": r.integers(
                    999, 29000, size=(n, 128)).tolist()}
                counts.append(n)
            _write(os.path.join(out, "reddit", f"{split}_tokens.json"),
                   {"users": names, "num_samples": counts, "user_data": data})
    else:
        raise SystemExit(f"unknown task {task}")


if __name__ == "__main__":
    main()
