"""json <-> hdf5 user-blob converters.

Parity target: reference ``utils/preprocessing/{create-hdf5,create-json,
from_json_to_hdf5}.py`` — converts the ``users/num_samples/user_data``
federated blob between json and hdf5.

Usage:
    python tools/convert_data.py input.json output.hdf5
    python tools/convert_data.py input.hdf5 output.json
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from msrflute_tpu.data.user_blob import (  # noqa: E402
    load_user_blob, save_user_blob_hdf5,
)


def main() -> None:
    if len(sys.argv) != 3:
        raise SystemExit(__doc__)
    src, dst = sys.argv[1], sys.argv[2]
    blob = load_user_blob(src)
    if dst.endswith((".hdf5", ".h5")):
        save_user_blob_hdf5(dst, blob)
    elif dst.endswith(".json"):
        payload = {
            "users": blob.user_list,
            "num_samples": blob.num_samples,
            "user_data": {u: {"x": np.asarray(x).tolist()}
                          for u, x in zip(blob.user_list, blob.user_data)},
        }
        if blob.user_labels is not None:
            payload["user_data_label"] = {
                u: np.asarray(y).tolist()
                for u, y in zip(blob.user_list, blob.user_labels)}
        with open(dst, "w") as fh:
            json.dump(payload, fh)
    else:
        raise SystemExit(f"unsupported output format: {dst}")
    print(f"converted {src} -> {dst} ({len(blob)} users)")


if __name__ == "__main__":
    main()
