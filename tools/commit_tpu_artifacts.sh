#!/bin/bash
# Commit whatever on-chip evidence exists RIGHT NOW.  Called after every
# queue job (tools/tpu_jobs.d/*.sh): the chip window can close at any
# moment, and artifacts that only land in history at end-of-queue are
# artifacts that may never land at all.
cd /root/repo
# One add per pathspec: a single missing file must not abort the whole
# batch (git add fails the entire call on any unmatched pathspec, which
# is exactly what stranded the first headline artifact).
# Canonical trajectory files stay tracked at repo root.
for f in BENCH_TPU_*.json FULLRUN_TPU_*.json \
  PROFILE_BERT_TPU.json PROFILE_BERT_GATHERED_TPU.json \
  PARITY_LONGRUN.json \
  PROFILE_EVAL_LR_TPU.json PROFILE_EVAL_CNN_TPU.json \
  FLASH_AUTO_VALIDATION.json DISPATCH_COST_TPU.json; do
  [ -e "$f" ] && git add -f "$f"
done
# Raw per-job captures (stdout json / stderr / logs) are repo-root
# strays by the ISSUE 7 hygiene rule: route them into artifacts/
# before committing so the root stays .gitignore-clean.
mkdir -p artifacts
for f in bench_tpu_*.json bench_tpu_*.err \
  bench_longctx.json bench_longctx.err \
  tpu_flash_validation.log tpu_pallas_tests.log \
  profile_cnn.json profile_cnn.err \
  bench_scale.json bench_scale.err \
  bench_bert_varlen.json bench_bert_varlen.err \
  digits_tpu.json digits_tpu.err \
  flash_crossover.json flash_crossover.err \
  tpu_secagg_ef_tests.log \
  fullrun_tpu.log profile_bert_tpu.log parity_longrun.log \
  profile_eval_tpu.log flash_auto_validation.err dispatch_cost.err \
  tpu_pallas_attention.log tpu_quant_kernel_probe.log; do
  [ -e "$f" ] && mv -f "$f" "artifacts/$f" && git add -f "artifacts/$f"
done
git diff --cached --quiet && exit 0
git commit -m "Add raw on-chip measurement artifacts (TPU queue checkpoint)

Committed immediately after a serialized tools/tpu_runner.sh queue job
so a closing chip window cannot strand the evidence."
