#!/bin/bash
# Commit whatever on-chip evidence exists RIGHT NOW.  Called after every
# queue job (tools/tpu_jobs.d/*.sh): the chip window can close at any
# moment, and artifacts that only land in history at end-of-queue are
# artifacts that may never land at all.
cd /root/repo
# One add per pathspec: a single missing file must not abort the whole
# batch (git add fails the entire call on any unmatched pathspec, which
# is exactly what stranded the first headline artifact).
for f in BENCH_TPU_*.json bench_tpu_*.json bench_tpu_*.err \
  bench_longctx.json bench_longctx.err \
  tpu_flash_validation.log tpu_pallas_tests.log \
  profile_cnn.json profile_cnn.err \
  bench_scale.json bench_scale.err \
  bench_bert_varlen.json bench_bert_varlen.err \
  digits_tpu.json digits_tpu.err \
  flash_crossover.json flash_crossover.err \
  tpu_secagg_ef_tests.log \
  FULLRUN_TPU_*.json fullrun_tpu.log \
  PROFILE_BERT_TPU.json PROFILE_BERT_GATHERED_TPU.json profile_bert_tpu.log \
  PARITY_LONGRUN.json parity_longrun.log \
  PROFILE_EVAL_LR_TPU.json PROFILE_EVAL_CNN_TPU.json profile_eval_tpu.log \
  FLASH_AUTO_VALIDATION.json flash_auto_validation.err \
  DISPATCH_COST_TPU.json dispatch_cost.err \
  tpu_pallas_attention.log tpu_quant_kernel_probe.log; do
  [ -e "$f" ] && git add -f "$f"
done
git diff --cached --quiet && exit 0
git commit -m "Add raw on-chip measurement artifacts (TPU queue checkpoint)

Committed immediately after a serialized tools/tpu_runner.sh queue job
so a closing chip window cannot strand the evidence."
