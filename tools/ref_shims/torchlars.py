"""torchlars shim: only imported by the reference optimizer factory; the
parity harness never selects the LARS optimizer."""


class LARS:  # pragma: no cover - guard only
    def __init__(self, *a, **k):
        raise RuntimeError("torchlars shim: LARS unavailable in this container")
