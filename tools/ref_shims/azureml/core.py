"""azureml.core shim: records run.log() calls to $REF_METRICS_OUT (jsonl)."""
import json
import os


class _OfflineRun:
    def __init__(self):
        self._path = os.environ.get("REF_METRICS_OUT")
        # AzureML-looking run id: e2e_trainer.py:221-222 derives the
        # experiment dir name from its dash-separated tail
        self.id = "OfflineRun-parity-harness-local-0000-0000"
        self.input_datasets = {}

    def log(self, name, value, **kw):
        if self._path:
            # reference models may log torch/numpy scalars (e.g. the
            # fed_shakespeare RNN's masked-accuracy tensor,
            # experiments/nlp_rnn_fedshakespeare/model.py:66) — coerce any
            # 0-d numeric to a plain float like the real AzureML SDK does
            if hasattr(value, "item") and not isinstance(value, dict):
                try:
                    value = value.item()
                except Exception:
                    value = str(value)
            with open(self._path, "a") as fh:
                fh.write(json.dumps({"name": str(name), "value": value}) + "\n")

    def log_row(self, name, **kw):
        self.log(name, kw)

    def add_properties(self, props):
        self.log("run_properties", props)

    def __getattr__(self, item):  # tag/display_name/etc. -> no-op
        return lambda *a, **k: None


class Run:
    @staticmethod
    def get_context():
        return _OfflineRun()
