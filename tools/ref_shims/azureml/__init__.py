# Offline stand-in for the azureml-sdk so the reference trainer can run in
# this container (zero egress, no AzureML workspace).  Only the surface the
# reference touches: azureml.core.Run.get_context() -> run.log(name, value).
