"""cerberus shim: schema validation becomes a no-op pass (the harness feeds
known-good configs; the real schema needs the cerberus package)."""


class Validator:
    def __init__(self, *a, **k):
        self.errors = {}

    def validate(self, *a, **k):
        return True

    def normalized(self, doc, *a, **k):
        return doc
