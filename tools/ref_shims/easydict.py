"""easydict shim: dict with attribute access (the published package's core)."""


class EasyDict(dict):
    def __init__(self, d=None, **kw):
        super().__init__()
        for k, v in dict(d or {}, **kw).items():
            self[k] = v

    def __setitem__(self, k, v):
        if isinstance(v, dict) and not isinstance(v, EasyDict):
            v = EasyDict(v)
        elif isinstance(v, (list, tuple)):
            v = type(v)(EasyDict(x) if isinstance(x, dict) else x for x in v)
        super().__setitem__(k, v)
        super().__setattr__(k, v)

    __setattr__ = __setitem__

    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError as exc:
            raise AttributeError(k) from exc
