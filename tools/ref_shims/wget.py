"""wget shim: zero-egress container; any download attempt must fail loudly."""


def download(*a, **k):  # pragma: no cover - guard only
    raise RuntimeError("wget shim: no network egress in this container")
