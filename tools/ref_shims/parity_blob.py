"""Shared blob loader for the parity adapter datasets.

The parity harness writes one json schema (``run_parity.write_blob``:
``users`` / ``num_samples`` / ``user_data[u]["x"]`` /
``user_data_label[u]``); each adapter dataset converts it to the dict the
reference loaders expect.  One loader here (this directory is already on
the reference run's PYTHONPATH, see ``run_parity.run_reference``) keeps
the schema contract in a single place — only the feature dtype differs
per task.
"""
import json

import numpy as np


def maybe_load(data, x_dtype=np.float32):
    """str path -> blob dict shaped like the reference loaders expect."""
    if not isinstance(data, str):
        return data
    with open(data) as fh:
        blob = json.load(fh)
    users = list(blob["users"])
    return {
        "users": users,
        "num_samples": list(blob["num_samples"]),
        "user_data": {
            u: np.asarray(blob["user_data"][u]["x"], dtype=x_dtype)
            for u in users},
        "user_data_label": {
            u: np.asarray(blob["user_data_label"][u], dtype=np.int64)
            for u in users},
    }
