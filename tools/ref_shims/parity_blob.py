"""Shared blob loader for the parity adapter datasets.

The parity harness writes one json schema (``run_parity.write_blob``:
``users`` / ``num_samples`` / ``user_data[u]["x"]`` /
``user_data_label[u]``); each adapter dataset converts it to the dict the
reference loaders expect.  One loader here (this directory is already on
the reference run's PYTHONPATH, see ``run_parity.run_reference``) keeps
the schema contract in a single place — only the feature dtype differs
per task.
"""
import json

import numpy as np


def maybe_load(data, x_dtype=np.float32):
    """str path -> blob dict shaped like the reference loaders expect.

    ``.hdf5`` blobs (the long-horizon corpus is ~1 GB — json text would
    be several GB and minutes of parsing) use the layout
    ``users / num_samples / user_data/<u>/{x,y}``."""
    if not isinstance(data, str):
        return data
    if data.endswith((".hdf5", ".h5")):
        import h5py
        with h5py.File(data, "r") as fh:
            users = [u.decode() if isinstance(u, bytes) else str(u)
                     for u in fh["users"][()]]
            return {
                "users": users,
                "num_samples": [int(n) for n in fh["num_samples"][()]],
                "user_data": {
                    u: np.asarray(fh["user_data"][u]["x"][()],
                                  dtype=x_dtype) for u in users},
                "user_data_label": {
                    u: np.asarray(fh["user_data"][u]["y"][()],
                                  dtype=np.int64) for u in users},
            }
    with open(data) as fh:
        blob = json.load(fh)
    users = list(blob["users"])
    return {
        "users": users,
        "num_samples": list(blob["num_samples"]),
        "user_data": {
            u: np.asarray(blob["user_data"][u]["x"], dtype=x_dtype)
            for u in users},
        "user_data_label": {
            u: np.asarray(blob["user_data_label"][u], dtype=np.int64)
            for u in users},
    }
