"""On-chip flash-vs-dense attention crossover sweep.

The committed longctx bench (`bench_tpu_longctx.json`) showed the Pallas
flash kernel SLOWER than XLA's dense softmax attention at L=2048
(flash_speedup 0.83-0.93): at that length the score matrix is small
enough that XLA's fused dense path is excellent.  Flash's O(L) memory is
the long-L story.  This tool measures, per sequence length and per
(block_q, block_k) tile choice, fwd+bwd wall time of both paths on the
bench's RingLM head geometry — the empirical basis for (a) the kernel's
default tiles and (b) the dense/flash auto-select crossover in
``models/ringlm.py``.

Writes one JSON object to stdout; stderr carries progress.  TPU-only by
assertion (a CPU "measurement" of interpret-mode kernels means nothing).
"""

from __future__ import annotations

import functools
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


from tools.timing_probe import grad_wall as _grad_wall  # noqa: E402


def main() -> int:
    import jax
    import jax.numpy as jnp

    assert jax.default_backend() == "tpu", jax.default_backend()
    from msrflute_tpu.ops.pallas_attention import flash_attention
    from msrflute_tpu.utils.backend import enable_compilation_cache
    import os
    enable_compilation_cache(os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache"))

    B, H, D = 4, 4, 64  # the longctx bench's RingLM head geometry
    rng = np.random.default_rng(0)
    res = {"backend": "tpu", "geometry": {"batch": B, "heads": H,
                                          "head_dim": D,
                                          "layout": "[B, L, H, D]",
                                          "dtype": "bfloat16"},
           "lengths": {}}

    def dense(q, k, v):
        # VERBATIM the ringlm local path (models/ringlm.py::_MHA else
        # branch) on [B, L, H, D] — same einsums, finfo-min mask, and the
        # bench's bf16 compute dtype (the TPU longctx protocol sets
        # dtype=bfloat16, so bf16 scores ARE the production dense path)
        L = q.shape[1]
        scale = 1.0 / jnp.sqrt(jnp.asarray(D, q.dtype))
        scores = jnp.einsum("blhd,bmhd->bhlm", q, k) * scale
        mask = jnp.tril(jnp.ones((L, L), bool))
        scores = jnp.where(mask[None, None], scores,
                           jnp.finfo(scores.dtype).min)
        p = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhlm,bmhd->blhd", p, v)

    grad_wall = _grad_wall

    for L in (1024, 2048, 4096, 8192, 16384):
        # flash_attention takes [B, L, H, D] (pallas_attention.py:427)
        q, k, v = (jnp.asarray(rng.normal(size=(B, L, H, D)),
                               jnp.bfloat16) for _ in range(3))
        row = {}
        if L <= 8192:  # dense bhlm scores at 16k: 4*4*16384^2 bf16 = 8.6 GB
            try:
                row["dense_fwd_bwd_ms"] = 1e3 * grad_wall(dense, q, k, v)
            except Exception as e:  # OOM is data, not failure
                row["dense_fwd_bwd_ms"] = None
                row["dense_error"] = type(e).__name__
        else:
            row["dense_fwd_bwd_ms"] = None
            row["dense_error"] = "skipped (score matrix ~8.6 GB bf16)"
        for bq, bk in ((128, 128), (128, 256), (256, 256), (128, 512),
                       (256, 512), (512, 512)):
            if bq > L or bk > L:
                continue
            fa = functools.partial(flash_attention, causal=True,
                                   block_q=bq, block_k=bk,
                                   force_flash=True)
            try:
                row[f"flash_{bq}x{bk}_fwd_bwd_ms"] = \
                    1e3 * grad_wall(fa, q, k, v)
            except Exception as e:
                row[f"flash_{bq}x{bk}_fwd_bwd_ms"] = None
                row[f"flash_{bq}x{bk}_error"] = repr(e)[:200]
        best = min((v for k2, v in row.items()
                    if k2.startswith("flash") and isinstance(v, float)),
                   default=None)
        if best and row.get("dense_fwd_bwd_ms"):
            row["flash_speedup_best"] = round(
                row["dense_fwd_bwd_ms"] / best, 3)
        res["lengths"][str(L)] = {k2: (round(v, 3)
                                       if isinstance(v, float) else v)
                                  for k2, v in row.items()}
        print(f"[flash_sweep] L={L}: {res['lengths'][str(L)]}",
              file=sys.stderr)
    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    sys.exit(main())
