#!/usr/bin/env python3
"""Endurance harness — the days-long-run drill, compressed (ISSUE 13).

Composes what PRs 3-12 built into ONE driver with a machine oracle:

- a heterogeneous client population (75% tiny clients + a heavy tail,
  the cohort-bucketing shape) trained for ``--rounds`` rounds under
  **chaos** (dropout + stragglers + checkpoint-IO faults), a **forced
  preemption + resume** at the midpoint (the PR-3 drill, driven by
  ``chaos.preempt_at_round``), **cohort shape-bucketing**, a
  **depth-3 pipeline**, and ``MSRFLUTE_STRICT_TRANSFERS=1``;
- flutescope endurance fully armed: windowed **rollups**
  (``rollups.jsonl``), the **flight recorder**, size-capped log
  rotation, and the longitudinal watchdogs (stall / rss_leak /
  throughput_drift);
- the pass/fail oracle is ``tools/scope health --gate`` over the run
  directory — rollups present, no critical watchdog firing, no
  abnormal flight record (the preemption flight is expected and
  benign).

``--seed-stall`` runs the adversarial arm instead: a deliberate hang is
injected into one round's host tail, the stall watchdog (action
``abort``) must fire, the flight record must carry it, and the health
oracle must gate **exit 3** — proving the tripwire trips.

The run also emits a BENCH_FLEET-style trajectory record
(``--report``): clients/sec, rounds/hour, padding-efficiency and
overlap-efficiency-% under an ``extras.endurance`` block shaped so
``tools/scope trend`` can walk a committed series of them.

Run: ``python tools/endurance.py`` (CPU, tens of seconds at the default
``--rounds 40``); ``tests/test_endurance.py`` drives :func:`run_endurance`
in-process with a smaller geometry.  Exit 0 iff every expectation held.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

# the fleet drill must exercise the SHARDED transfer plane (per-shard
# slot allocation, sharded page-in/writeback, migrations): force a
# 4-virtual-device CPU mesh before anything initializes the backend —
# the same XLA_FLAGS emulation the test conftest uses at 8.  An
# ambient larger count (e.g. running under the test env) is kept.
if "--fleet" in (sys.argv or []):
    from msrflute_tpu.utils.backend import force_cpu_backend
    force_cpu_backend(4)
elif "--infra" in (sys.argv or []):
    # the infra drill's mesh-elastic resume needs headroom to SHRINK:
    # leg 1 trains on an 8-shard clients mesh, leg 2 resumes on 4
    from msrflute_tpu.utils.backend import force_cpu_backend
    force_cpu_backend(8)

#: the chaos drill: every client-fault class live, plus the forced
#: midpoint preemption the driver adds per-run
CHAOS = {
    "seed": 11,
    "dropout_rate": 0.15,
    "straggler_rate": 0.15,
    "straggler_inflation": 2.0,
    "ckpt_io_error_rate": 0.1,
}

#: endurance telemetry: small windows so a short drill still flushes
#: several rollup records; longitudinal watchdogs on (log), stall armed
#: to ABORT only in the seeded-stall arm
TELEMETRY = {
    "enable": True,
    "rollup_window": 4,
    "max_log_mb": 8,
    "watchdog": {
        "rss_leak_action": "log",
        "rss_leak_window": 8,
        "rss_leak_mb_per_round": 256.0,
        "throughput_drift_action": "log",
        "throughput_drift_window": 8,
        "throughput_drift_factor": 3.0,
    },
}


def _hetero_dataset(num_users: int, seed: int = 0):
    """75% tiny clients + a log-spaced heavy tail (the skew cohort
    bucketing exists for), on the LR protocol's feature geometry."""
    import numpy as np
    from msrflute_tpu.data import ArraysDataset

    rng = np.random.default_rng(seed)
    users, per = [], []
    for u in range(num_users):
        if u % 4 == 0:
            n = int(8 * 2 ** (u % 3 + 1))  # heavy tail: 16/32/64
        else:
            n = 8
        users.append(f"u{u}")
        per.append({
            "x": rng.normal(size=(n, 8)).astype(np.float32),
            "y": rng.integers(0, 4, n).astype(np.int32)})
    return ArraysDataset(users, per)


def _config(rounds: int, preempt_at: int, stall: bool):
    from msrflute_tpu.config import FLUTEConfig

    telemetry = json.loads(json.dumps(TELEMETRY))  # deep copy
    if stall:
        telemetry["watchdog"].update({
            "stall_action": "abort",
            # tuned to the injected 2 s hang against ~ms CPU rounds
            "stall_poll_secs": 0.05,
            "stall_grace_secs": 0.5,
            "stall_factor": 10.0,
        })
    return FLUTEConfig.from_dict({
        "model_config": {"model_type": "LR", "num_classes": 4,
                         "input_dim": 8},
        "strategy": "fedavg",
        "server_config": {
            "max_iteration": rounds,
            "num_clients_per_iteration": 8,
            "initial_lr_client": 0.1,
            "rounds_per_step": 2,
            "pipeline_depth": 3,
            "optimizer_config": {"type": "sgd", "lr": 1.0},
            "val_freq": 1000, "initial_val": False,
            "resume_from_checkpoint": True,
            "data_config": {},
            "cohort_bucketing": {"max_buckets": 3, "slack": 2.0},
            "chaos": dict(CHAOS, preempt_at_round=preempt_at),
            "checkpoint_retry": {"retries": 3, "backoff_base_s": 0.0,
                                 "jitter": 0.0},
            "telemetry": telemetry,
        },
        "client_config": {
            "optimizer_config": {"type": "sgd", "lr": 0.1},
            "data_config": {"train": {"batch_size": 4}}},
    })


def run_endurance(rounds: int = 40, num_users: int = 24,
                  out_dir: str | None = None,
                  seed_stall: bool = False,
                  report_path: str | None = None) -> dict:
    """Drive the full drill; returns the result record (also written to
    ``report_path``).  Raises AssertionError on any broken expectation
    — the CI smoke job runs this under ``python tools/endurance.py``."""
    os.environ.setdefault("MSRFLUTE_STRICT_TRANSFERS", "1")
    from msrflute_tpu.engine import OptimizationServer
    from msrflute_tpu.models import make_task
    from msrflute_tpu.telemetry.scope_cli import health, summarize
    from msrflute_tpu.utils.logging import init_logging

    out_dir = out_dir or tempfile.mkdtemp(prefix="endurance_")
    init_logging(out_dir)
    dataset = _hetero_dataset(num_users)
    preempt_at = max(rounds // 2, 1)
    tic = time.time()

    # ---- leg 1: train into the forced preemption ---------------------
    cfg = _config(rounds, preempt_at, stall=False)
    server = OptimizationServer(make_task(cfg.model_config), cfg,
                                dataset, model_dir=out_dir, seed=0)
    server.train()
    assert server.preempted, "forced preemption never fired"
    assert server.state.round >= preempt_at, (
        server.state.round, preempt_at)
    flight_path = os.path.join(out_dir, "telemetry", "flight.json")
    assert os.path.exists(flight_path), \
        "preemption did not persist flight.json"
    rollups_path = os.path.join(out_dir, "telemetry", "rollups.jsonl")
    assert os.path.exists(rollups_path), \
        "no rollups.jsonl after leg 1 — incremental flush broken"

    # ---- leg 2: resume to completion (optionally stall-seeded) -------
    cfg2 = _config(rounds, preempt_at, stall=seed_stall)
    server2 = OptimizationServer(make_task(cfg2.model_config), cfg2,
                                 dataset, model_dir=out_dir, seed=0)
    stalled = False
    if seed_stall:
        drain = server2._drain_chunk
        hit = {"n": 0}

        def hanging_drain(chunk, vf, rf):
            hit["n"] += 1
            # hang on the SECOND drain: the first drain's heartbeat has
            # armed the monitor and seeded the trailing median by then,
            # and even the smallest test geometry reaches drain 2.  The
            # hang must out-sleep the LIVE limit — the trailing median
            # here includes leg-2 recompile rounds, so a fixed sleep
            # would under-shoot exactly when compiles are slow
            if hit["n"] == 2:
                wd = server2.scope.watchdog
                limit = max(float(wd.cfg["stall_factor"]) *
                            float(wd._beat[1]),
                            float(wd.cfg["stall_grace_secs"]))
                time.sleep(limit + 1.0)  # the "hung dispatch" stand-in
            drain(chunk, vf, rf)

        server2._drain_chunk = hanging_drain
    try:
        server2.train()
    except BaseException as exc:  # KeyboardInterrupt from the monitor
        stalled = True
        print(f"endurance: stall unwind via {type(exc).__name__}")
    if seed_stall and not stalled:
        # the monitor's interrupt landed as a graceful SIGINT
        # preemption (the installed handler's territory) — the stall
        # FINDING is the contract either way
        stalled = any(f.get("kind") == "stall"
                      for f in server2.scope.watchdog.findings)
    wall = time.time() - tic

    # ---- the oracle --------------------------------------------------
    verdict = health(out_dir)
    gate_exit = 0 if verdict["ok"] else 3
    if seed_stall:
        assert stalled, "seeded stall never fired the stall watchdog"
        assert gate_exit == 3, (
            "seeded-stall run must gate unhealthy", verdict)
        kinds = {f["check"] for f in verdict["findings"]}
        assert "watchdog_stall" in kinds, verdict
    else:
        assert server2.state.round == rounds, (
            server2.state.round, rounds)
        assert gate_exit == 0, ("clean run must gate healthy", verdict)
        assert verdict["rollup_windows"] >= 2, verdict

    # ---- trajectory record (BENCH_FLEET shape; scope trend walks the
    # extras.<name>.secs_per_round convention) -------------------------
    summary = summarize(out_dir)
    card = (summary.get("scorecard") or {}) if isinstance(
        summary.get("scorecard"), dict) else {}
    secs_p50 = card.get("round_secs_p50")
    rollup_last = (verdict.get("last_window") or {})
    record = {
        "kind": "endurance",
        "metric": "endurance_secs_per_round",
        "value": secs_p50,
        "rounds": rounds,
        "seed_stall": bool(seed_stall),
        "wall_secs": round(wall, 2),
        "health": {"ok": verdict["ok"],
                   "findings": verdict["findings"],
                   "warnings": verdict["warnings"]},
        "extras": {
            "endurance": {
                "secs_per_round": secs_p50,
                "rounds_per_hour": (round(3600.0 / secs_p50, 1)
                                    if secs_p50 else None),
                "clients_per_sec": rollup_last.get("clients_per_sec"),
                "padding_efficiency": card.get("padding_efficiency"),
                "overlap_efficiency_pct":
                    card.get("overlap_efficiency_pct"),
                "mfu_p50": card.get("mfu_p50"),
                "recompiles": card.get("recompiles"),
                "rollup_windows": verdict.get("rollup_windows"),
                "preempt_resume": True,
            },
        },
    }
    if report_path:
        tmp = report_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(record, fh, indent=1, sort_keys=True)
        os.replace(tmp, report_path)
    return record


#: the flash-crowd drill's arrival plane (RUNBOOK "Flash-crowd
#: drill"): a bursty trace — quiet off-burst floor, periodic flash
#: crowds — fired buffered, so FedBuff's discount consumes the TRUE
#: traced per-client staleness
TRAFFIC = {
    "seed": 9, "mode": "buffered", "trace": "bursty",
    "rate": 2.0, "burst_rate": 24.0, "burst_every": 12, "burst_len": 4,
}


def _traffic_config(rounds: int, preempt_at):
    """The arrival-plane posture: buffered FedBuff on the bursty trace
    under cohort bucketing, a depth-3 pipeline and strict transfers,
    with the forced midpoint preemption driving the resume replay."""
    from msrflute_tpu.config import FLUTEConfig

    telemetry = json.loads(json.dumps(TELEMETRY))
    sc = {
        "max_iteration": rounds,
        "num_clients_per_iteration": 8,
        "initial_lr_client": 0.1,
        "rounds_per_step": 2,
        "pipeline_depth": 3,
        "optimizer_config": {"type": "sgd", "lr": 1.0},
        "val_freq": 1000, "initial_val": False,
        "resume_from_checkpoint": True,
        "data_config": {},
        "cohort_bucketing": {"max_buckets": 3, "slack": 2.0},
        "fedbuff": {"max_staleness": 4},
        "traffic": dict(TRAFFIC),
        "checkpoint_retry": {"retries": 3, "backoff_base_s": 0.0,
                             "jitter": 0.0},
        "telemetry": telemetry,
    }
    if preempt_at is not None:
        # zero-rate chaos block: ONLY the preemption drill rides it —
        # bit-identical to no client faults at all
        sc["chaos"] = {"seed": 11, "preempt_at_round": preempt_at}
    return FLUTEConfig.from_dict({
        "model_config": {"model_type": "LR", "num_classes": 4,
                         "input_dim": 8},
        "strategy": "fedbuff",
        "server_config": sc,
        "client_config": {
            "optimizer_config": {"type": "sgd", "lr": 0.1},
            "data_config": {"train": {"batch_size": 4}}},
    })


def run_traffic(rounds: int = 24, num_users: int = 24,
                out_dir: str | None = None,
                report_path: str | None = None) -> dict:
    """The flash-crowd drill (ISSUE 19 acceptance): buffered
    FedBuff rounds fired by a seeded bursty arrival trace, under
    cohort bucketing + a depth-3 pipeline + strict transfers, with a
    forced midpoint preemption + resume.  Asserts:

    - the engine compiled the traced-staleness DATA operand (arrival
      dynamics ride operands, never the program — so the resumed leg
      must be recompile-flat past warmup);
    - the resumed run REPLAYS the identical arrival timeline: every
      fire's (tick, cohort, staleness) matches a fresh schedule built
      from the same seed;
    - ``tools/scope health --gate`` exits 0 and the scorecard's
      traffic card accounts for every fired round;

    and emits a BENCH_FLEET-style trajectory record under
    ``extras.traffic`` so ``tools/scope trend`` can walk a committed
    series of them.
    """
    os.environ.setdefault("MSRFLUTE_STRICT_TRANSFERS", "1")
    import numpy as np

    from msrflute_tpu.engine import OptimizationServer
    from msrflute_tpu.models import make_task
    from msrflute_tpu.telemetry.scope_cli import health, summarize
    from msrflute_tpu.traffic import make_traffic
    from msrflute_tpu.utils.logging import init_logging

    out_dir = out_dir or tempfile.mkdtemp(prefix="traffic_")
    init_logging(out_dir)
    dataset = _hetero_dataset(num_users)
    preempt_at = max(rounds // 2, 1)
    tic = time.time()

    # ---- leg 1: into the forced preemption ---------------------------
    cfg = _traffic_config(rounds, preempt_at)
    server = OptimizationServer(make_task(cfg.model_config), cfg,
                                dataset, model_dir=out_dir, seed=0)
    assert server.traffic is not None, "arrival plane not engaged"
    assert server.engine.traffic_staleness, (
        "fedbuff + buffered must compile the traced-staleness operand")
    server.train()
    assert server.preempted, "forced preemption never fired"

    # ---- leg 2: resume to completion, recompile-flat past warmup -----
    cfg2 = _traffic_config(rounds, preempt_at)
    server2 = OptimizationServer(make_task(cfg2.model_config), cfg2,
                                 dataset, model_dir=out_dir, seed=0)
    recompiles_per_chunk: list = []
    drain = server2._drain_chunk

    def observing_drain(chunk, vf, rf):
        drain(chunk, vf, rf)
        recompiles_per_chunk.append(int(server2.engine.recompile_count))

    server2._drain_chunk = observing_drain
    server2.train()
    assert server2.state.round == rounds, (server2.state.round, rounds)
    warm = min(2, max(len(recompiles_per_chunk) - 1, 0))
    steady = recompiles_per_chunk[warm:]
    assert not steady or steady[-1] == steady[0], (
        "post-warmup recompiles", recompiles_per_chunk)

    # ---- replay oracle: resumed timeline == fresh timeline -----------
    fresh = make_traffic(
        {"traffic": dict(TRAFFIC), "num_clients_per_iteration": 8},
        len(dataset))
    for r in range(rounds):
        a, b = server2.traffic.fire(r), fresh.fire(r)
        assert int(a["tick"]) == int(b["tick"]), (r, a, b)
        assert np.array_equal(a["cohort"], b["cohort"]), (
            "resume replayed a different cohort", r)
        assert np.array_equal(a["staleness"], b["staleness"]), (
            "resume replayed different staleness", r)
    wall = time.time() - tic

    # ---- the oracle --------------------------------------------------
    verdict = health(out_dir)
    assert verdict["ok"], ("traffic run must gate healthy", verdict)

    summary = summarize(out_dir)
    card = (summary.get("scorecard") or {}) if isinstance(
        summary.get("scorecard"), dict) else {}
    tcard = card.get("traffic") or {}
    assert tcard, "scorecard must carry the traffic card"
    counters = tcard.get("counters") or {}
    assert int(counters.get("fires", 0)) == rounds, counters
    secs_p50 = card.get("round_secs_p50")
    record = {
        "kind": "traffic",
        "metric": "traffic_secs_per_round",
        "value": secs_p50,
        "rounds": rounds,
        "wall_secs": round(wall, 2),
        "health": {"ok": verdict["ok"],
                   "findings": verdict["findings"],
                   "warnings": verdict["warnings"]},
        "extras": {
            "traffic": {
                "secs_per_round": secs_p50,
                "rounds_per_hour": (round(3600.0 / secs_p50, 1)
                                    if secs_p50 else None),
                "trace": TRAFFIC["trace"],
                "mode": TRAFFIC["mode"],
                "arrival_rate": tcard.get("arrival_rate"),
                "mean_buffer_occupancy":
                    tcard.get("mean_buffer_occupancy"),
                "stale_hist": tcard.get("stale_hist"),
                "counters": counters,
                "recompiles_per_chunk": recompiles_per_chunk,
                "preempt_resume": True,
            },
        },
    }
    if report_path:
        tmp = report_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(record, fh, indent=1, sort_keys=True)
        os.replace(tmp, report_path)
    return record


def _fleet_config(rounds: int, cohort: int, preempt_at):
    """The fleet posture: fused-carry SCAFFOLD (the richest carry
    state: a pageable per-client table plus the resident server
    control) under chaos + cohort bucketing + a depth-3 pipeline, with
    the ``fleet`` block on and the rss_leak watchdog armed."""
    from msrflute_tpu.config import FLUTEConfig

    telemetry = json.loads(json.dumps(TELEMETRY))
    chaos = dict(CHAOS)
    if preempt_at is not None:
        chaos["preempt_at_round"] = preempt_at
    return FLUTEConfig.from_dict({
        "model_config": {"model_type": "LR", "num_classes": 4,
                         "input_dim": 8},
        "strategy": "scaffold",
        "server_config": {
            "max_iteration": rounds,
            "num_clients_per_iteration": cohort,
            "initial_lr_client": 0.1,
            "fused_carry": True,
            "pipeline_depth": 3,
            "optimizer_config": {"type": "sgd", "lr": 1.0},
            "val_freq": 100000, "initial_val": False,
            "resume_from_checkpoint": True,
            "data_config": {},
            "cohort_bucketing": {"max_buckets": 3, "slack": 2.0},
            "chaos": chaos,
            "fleet": {"enable": True},
            "telemetry": telemetry,
        },
        "client_config": {
            "optimizer_config": {"type": "sgd", "lr": 0.1},
            "data_config": {"train": {"batch_size": 4}}},
    })


def run_fleet(rounds: int = 8, population: int = 1_000_000,
              cohort: int = 1024, out_dir: str | None = None,
              report_path: str | None = None) -> dict:
    """The fleet-scale smoke drill (ISSUE 14 acceptance): a synthetic
    10^6-user population, cohort ~1k, chaos + bucketing + a depth-3
    pipeline under ``MSRFLUTE_STRICT_TRANSFERS=1``, with a forced
    midpoint preemption + resume.  Asserts:

    - device carry HBM is bounded by the PAGE POOL, not the population
      (the ``ci`` table's leading dim is the slot count);
    - zero post-warmup recompiles (the engine's always-on counter is
      flat across the resumed leg's steady-state chunks);
    - host RSS stays flat (the rss_leak watchdog never fires — it is
      armed) and ``scope health --gate`` exits 0;

    and emits a BENCH_FLEET trajectory record (clients/sec,
    rounds/hour, padding-efficiency + paging counters) under
    ``extras.fleet`` so ``tools/scope trend`` can walk a committed
    series of them.
    """
    os.environ.setdefault("MSRFLUTE_STRICT_TRANSFERS", "1")
    from msrflute_tpu.data.fleet import SyntheticFleetDataset
    from msrflute_tpu.engine import OptimizationServer
    from msrflute_tpu.models import make_task
    from msrflute_tpu.telemetry.scope_cli import health, summarize
    from msrflute_tpu.utils.logging import init_logging

    out_dir = out_dir or tempfile.mkdtemp(prefix="fleet_")
    init_logging(out_dir)
    dataset = SyntheticFleetDataset(population, cache_users=512)
    preempt_at = max(rounds // 2, 1)
    tic = time.time()

    # ---- leg 1: into the forced preemption ---------------------------
    cfg = _fleet_config(rounds, cohort, preempt_at)
    server = OptimizationServer(make_task(cfg.model_config), cfg,
                                dataset, model_dir=out_dir, seed=0)
    pool_slots = server.fleet_pager.n_slots
    mesh_shards = server.fleet_pager.mesh_shards
    assert pool_slots < population, (pool_slots, population)
    server.train()
    assert server.preempted, "forced preemption never fired"
    ci_rows = int(server.state.strategy_state["ci"].shape[0])
    assert ci_rows == pool_slots, (
        "carry HBM must be bounded by the page pool, not N",
        ci_rows, pool_slots)
    # mesh-sharded pool (ISSUE 15): each DEVICE holds slots/mesh_size
    # rows, not the whole pool — a replicated table here is exactly the
    # transfer-plane regression the sharded spec removed
    per_dev_rows = {s.data.shape[0] for s in
                    server.state.strategy_state["ci"].addressable_shards}
    assert per_dev_rows == {pool_slots // mesh_shards}, (
        "pool HBM must be slots/mesh_size rows per device",
        per_dev_rows, pool_slots, mesh_shards)

    # ---- leg 2: resume to completion, recompile-flat past warmup -----
    cfg2 = _fleet_config(rounds, cohort, preempt_at)
    server2 = OptimizationServer(make_task(cfg2.model_config), cfg2,
                                 dataset, model_dir=out_dir, seed=0)
    recompiles_per_chunk: list = []
    drain = server2._drain_chunk

    def observing_drain(chunk, vf, rf):
        drain(chunk, vf, rf)
        recompiles_per_chunk.append(int(server2.engine.recompile_count))

    server2._drain_chunk = observing_drain
    server2.train()
    assert server2.state.round == rounds, (server2.state.round, rounds)
    # zero post-warmup recompiles: once the resumed leg's program set
    # warmed (first two drained chunks cover the bucket-grid variants),
    # the counter must not move again
    warm = min(2, max(len(recompiles_per_chunk) - 1, 0))
    steady = recompiles_per_chunk[warm:]
    assert not steady or steady[-1] == steady[0], (
        "post-warmup recompiles", recompiles_per_chunk)
    wall = time.time() - tic

    # ---- the oracle --------------------------------------------------
    verdict = health(out_dir)
    gate_exit = 0 if verdict["ok"] else 3
    assert gate_exit == 0, ("fleet run must gate healthy", verdict)
    rss_fires = [f for f in (verdict.get("findings") or [])
                 if "rss" in str(f.get("check", ""))]
    assert not rss_fires, ("host RSS leaked across rounds", rss_fires)

    summary = summarize(out_dir)
    card = (summary.get("scorecard") or {}) if isinstance(
        summary.get("scorecard"), dict) else {}
    secs_p50 = card.get("round_secs_p50")
    rollup_last = (verdict.get("last_window") or {})
    record = {
        "kind": "fleet",
        "metric": "fleet_secs_per_round",
        "value": secs_p50,
        "rounds": rounds,
        "population": population,
        "cohort": cohort,
        "wall_secs": round(wall, 2),
        "health": {"ok": verdict["ok"],
                   "findings": verdict["findings"],
                   "warnings": verdict["warnings"]},
        "extras": {
            "fleet": {
                "secs_per_round": secs_p50,
                "rounds_per_hour": (round(3600.0 / secs_p50, 1)
                                    if secs_p50 else None),
                "clients_per_sec": rollup_last.get("clients_per_sec"),
                "padding_efficiency": card.get("padding_efficiency"),
                "page_pool_slots": pool_slots,
                "mesh_shards": mesh_shards,
                # transfer-plane accounting (ISSUE 15): per-device vs
                # total paging bytes + prefetch coverage, so `scope
                # diff/trend --gate` catches a replication regression
                # in the committed BENCH_FLEET series
                "page_in_bytes_per_device": card.get(
                    "fleet_page_in_bytes_per_device"),
                "writeback_bytes_per_device": card.get(
                    "fleet_writeback_bytes_per_device"),
                "prefetch_hit_rate": card.get(
                    "fleet_prefetch_hit_rate"),
                "paging": card.get("fleet"),
                "lazy_cache": card.get("lazy_cache"),
                "recompiles_per_chunk": recompiles_per_chunk,
                "preempt_resume": True,
            },
        },
    }
    if report_path:
        tmp = report_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(record, fh, indent=1, sort_keys=True)
        os.replace(tmp, report_path)
    return record


def _infra_config(rounds: int, preempt_at, slots: int):
    """The infrastructure-fault posture (RUNBOOK "Infrastructure-fault
    drill"): the PROVEN mesh-elastic parity geometry (cohort 4 — both
    meshes >= cohort) under faults on every host-service surface, with
    a tiny host cache forcing spill-through so the store streams
    actually fire, and a depth-3 pipeline so the fleet-prefetch daemon
    ENGAGES (serial mode never stages ahead, so the prefetch-kill leg
    of the drill needs the pipelined loop).

    Client dropout/straggler chaos is deliberately OFF: those draws
    are keyed per padded cohort slot, so their streams are
    mesh-geometry-dependent and an 8-shard and a 4-shard run see
    different fault schedules — which is chaos working as designed,
    not an elastic-resume defect.  The drill's parity oracle needs the
    fault plane whose WHOLE contract is "never touches model state":
    infra faults plus checkpoint-IO faults, which are absorbed by the
    retry ladders regardless of mesh shape."""
    from msrflute_tpu.config import FLUTEConfig

    telemetry = json.loads(json.dumps(TELEMETRY))
    chaos = {"seed": CHAOS["seed"],
             "ckpt_io_error_rate": CHAOS["ckpt_io_error_rate"]}
    # the escalate/drop surfaces (spill, writer) tolerate hot rates;
    # the RAISE surfaces (read, writeback) abort the run on retry
    # exhaustion by design, so their rates stay low enough that the
    # 4-attempt ladder absorbs every injected blip on the seeded stream
    chaos["infra"] = {
        "store_write_error_rate": 0.2,
        "store_read_error_rate": 0.05,
        # rate 1.0 KILLS the fleet-prefetch daemon on its first stage:
        # the drill must cross the prefetch_degraded -> permanent
        # cold-path fallback, not just absorb a blip
        "prefetch_error_rate": 1.0,
        "writer_error_rate": 0.2,
        "writeback_error_rate": 0.05,
    }
    if preempt_at is not None:
        chaos["preempt_at_round"] = preempt_at
    return FLUTEConfig.from_dict({
        "model_config": {"model_type": "LR", "num_classes": 4,
                         "input_dim": 8},
        "strategy": "scaffold",
        "server_config": {
            "max_iteration": rounds,
            "num_clients_per_iteration": 4,
            "initial_lr_client": 0.1,
            "fused_carry": True,
            "rounds_per_step": 1,
            "pipeline_depth": 3,
            "optimizer_config": {"type": "sgd", "lr": 1.0},
            "val_freq": 100000, "initial_val": False,
            "resume_from_checkpoint": True,
            "data_config": {},
            "chaos": chaos,
            "checkpoint_retry": {"retries": 4, "backoff_base_s": 0.0,
                                 "jitter": 0.0},
            "fleet": {"page_pool_slots": slots, "host_cache_rows": 2,
                      "spill_freq": 1},
            "telemetry": telemetry,
        },
        "client_config": {
            "optimizer_config": {"type": "sgd", "lr": 0.1},
            "data_config": {"train": {"batch_size": 4}}},
    })


def run_infra(rounds: int = 12, num_users: int = 32,
              out_dir: str | None = None,
              report_path: str | None = None) -> dict:
    """The infrastructure-fault drill (ISSUE 20 acceptance): a
    scaffold + fused_carry fleet run on an 8-shard virtual mesh under
    seeded faults on EVERY host-service surface (row-store spill/read,
    a killed prefetch daemon, rollup writer, writeback fetch) plus
    checkpoint-IO faults, forcibly preempted at the midpoint and resumed
    on a FOUR-shard mesh with a re-quantized page pool.  Asserts:

    - final params bit-identical to the never-preempted 8-shard run
      under the same fault streams (the ladder absorbs every injected
      blip without touching model state; the elastic resume re-derives
      slot geometry without re-associating the round sum);
    - every degradation is observable: the infra fault ledger counts
      each surface, the dead daemon shows up as prefetch faults;
    - ``tools/scope health --gate`` exits 0 over the run dir;

    and emits a BENCH_INFRA trajectory record under ``extras.infra``.
    """
    os.environ.setdefault("MSRFLUTE_STRICT_TRANSFERS", "1")
    import numpy as np
    from jax.flatten_util import ravel_pytree

    import jax
    from msrflute_tpu.engine import OptimizationServer
    from msrflute_tpu.models import make_task
    from msrflute_tpu.parallel.mesh import make_mesh
    from msrflute_tpu.telemetry.scope_cli import health, summarize
    from msrflute_tpu.utils.logging import init_logging

    out_dir = out_dir or tempfile.mkdtemp(prefix="infra_")
    init_logging(out_dir)
    dataset = _hetero_dataset(num_users)
    preempt_at = max(rounds // 2, 1)
    tic = time.time()

    def _flat(state):
        return np.asarray(
            ravel_pytree(jax.device_get(state.params))[0])

    # ---- reference: never-preempted 8-shard run, same fault streams --
    cfg_ref = _infra_config(rounds, None, slots=32)
    ref_state = OptimizationServer(
        make_task(cfg_ref.model_config), cfg_ref, dataset,
        model_dir=tempfile.mkdtemp(prefix="infra_ref_"),
        mesh=make_mesh(num_devices=8), seed=0).train()
    ref = _flat(ref_state)

    # ---- leg 1: 8 shards into the forced preemption ------------------
    cfg = _infra_config(rounds, preempt_at, slots=32)
    server = OptimizationServer(make_task(cfg.model_config), cfg,
                                dataset, model_dir=out_dir,
                                mesh=make_mesh(num_devices=8), seed=0)
    server.train()
    assert server.preempted, "forced preemption never fired"
    leg1 = dict(server.chaos.infra.counters)
    assert leg1["store_write_faults"] > 0, leg1
    assert leg1["prefetch_faults"] > 0, (
        "the prefetch daemon was never faulted", leg1)

    # ---- leg 2: resume on 4 shards with a re-quantized pool ----------
    cfg2 = _infra_config(rounds, preempt_at, slots=16)
    server2 = OptimizationServer(make_task(cfg2.model_config), cfg2,
                                 dataset, model_dir=out_dir,
                                 mesh=make_mesh(num_devices=4), seed=0)
    res_state = server2.train()
    wall = time.time() - tic
    assert res_state.round == rounds, (res_state.round, rounds)
    assert not server2.preempted
    assert server2.fleet_pager.mesh_shards == 4, \
        server2.fleet_pager.mesh_shards
    res = _flat(res_state)
    assert np.array_equal(ref, res), (
        "8 -> 4 shard elastic resume under infra faults diverged from "
        "the never-preempted 8-shard run")
    leg2 = dict(server2.chaos.infra.counters)

    # ---- the oracle --------------------------------------------------
    verdict = health(out_dir)
    assert verdict["ok"], ("infra drill must gate healthy", verdict)
    assert verdict["rollup_windows"] >= 2, verdict

    summary = summarize(out_dir)
    card = (summary.get("scorecard") or {}) if isinstance(
        summary.get("scorecard"), dict) else {}
    assert (card.get("infra_faults") or {}).get(
        "store_write_faults", 0) > 0, (
        "scorecard must carry the infra fault ledger", card)
    secs_p50 = card.get("round_secs_p50")
    record = {
        "kind": "infra",
        "metric": "infra_secs_per_round",
        "value": secs_p50,
        "rounds": rounds,
        "wall_secs": round(wall, 2),
        "health": {"ok": verdict["ok"],
                   "findings": verdict["findings"],
                   "warnings": verdict["warnings"]},
        "extras": {
            "infra": {
                "secs_per_round": secs_p50,
                "rounds_per_hour": (round(3600.0 / secs_p50, 1)
                                    if secs_p50 else None),
                "mesh_shards_from": 8,
                "mesh_shards_to": 4,
                "pool_slots_from": 32,
                "pool_slots_to": 16,
                "fault_rates": cfg2.server_config["chaos"]["infra"],
                "faults_leg1": leg1,
                "faults_leg2": leg2,
                "elastic_bit_identical": True,
                "preempt_resume": True,
            },
        },
    }
    if report_path:
        tmp = report_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(record, fh, indent=1, sort_keys=True)
        os.replace(tmp, report_path)
    return record


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    # None sentinel: each posture resolves its own default (40-round
    # endurance, 8-round fleet) — an EXPLICIT --rounds always wins
    ap.add_argument("--rounds", type=int, default=None)
    ap.add_argument("--users", type=int, default=24)
    ap.add_argument("--out-dir", default=None)
    ap.add_argument("--seed-stall", action="store_true",
                    help="adversarial arm: inject a hang, expect the "
                         "stall watchdog + health gate 3")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet posture: synthetic million-user "
                         "population, paged carry, O(cohort) host state "
                         "(ISSUE 14); emits a BENCH_FLEET record")
    ap.add_argument("--fleet-population", type=int, default=1_000_000)
    ap.add_argument("--fleet-cohort", type=int, default=1024)
    ap.add_argument("--infra", action="store_true",
                    help="infrastructure-fault posture: fleet paging "
                         "under faults on every host-service surface, "
                         "a forced midpoint preempt and an 8 -> 4 shard "
                         "mesh-elastic resume (ISSUE 20); emits a "
                         "BENCH_INFRA record")
    ap.add_argument("--traffic", action="store_true",
                    help="flash-crowd posture: buffered FedBuff fired "
                         "by a seeded bursty arrival trace, preempt + "
                         "resume replay (ISSUE 19); emits a "
                         "BENCH_FLEET-style record")
    ap.add_argument("--report", default=None,
                    help="write the trajectory record here")
    args = ap.parse_args(argv)
    if args.infra:
        record = run_infra(rounds=(12 if args.rounds is None
                                   else args.rounds),
                           num_users=args.users,
                           out_dir=args.out_dir,
                           report_path=args.report)
        print(json.dumps(record, indent=1, sort_keys=True))
        ok = record["health"]["ok"]
        print("infra:", "PASS" if ok else "FAIL")
        return 0 if ok else 1
    if args.traffic:
        record = run_traffic(rounds=(24 if args.rounds is None
                                     else args.rounds),
                             num_users=args.users,
                             out_dir=args.out_dir,
                             report_path=args.report)
        print(json.dumps(record, indent=1, sort_keys=True))
        ok = record["health"]["ok"]
        print("traffic:", "PASS" if ok else "FAIL")
        return 0 if ok else 1
    if args.fleet:
        record = run_fleet(rounds=(8 if args.rounds is None
                                   else args.rounds),
                           population=args.fleet_population,
                           cohort=args.fleet_cohort,
                           out_dir=args.out_dir,
                           report_path=args.report)
        print(json.dumps(record, indent=1, sort_keys=True))
        ok = record["health"]["ok"]
        print("fleet:", "PASS" if ok else "FAIL")
        return 0 if ok else 1
    record = run_endurance(rounds=(40 if args.rounds is None
                                   else args.rounds),
                           num_users=args.users,
                           out_dir=args.out_dir,
                           seed_stall=args.seed_stall,
                           report_path=args.report)
    print(json.dumps(record, indent=1, sort_keys=True))
    ok = record["health"]["ok"] if not args.seed_stall else \
        not record["health"]["ok"]
    print("endurance:", "PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
