"""Chaos smoke: an N-round run with dropout + straggler + checkpoint-IO
faults AND adversarial update corruption enabled (fluteshield screening
on), asserting every injected-fault class fired and the quarantine
counters exactly match the seeded injection schedule.

The cheap end-to-end proof that the deterministic fault-injection path
(``server_config.chaos`` -> fused-round fault operands -> packed-stats
counters -> bench contract) is alive: dropout/straggling fold into the
round program, NaN/scale/sign-flip corruption hits the transmitted
payloads, fluteshield quarantines what the schedule poisoned, IO faults
exercise the checkpoint retry machinery, and the emitted JSON carries
the chaos + robust blocks + counters exactly like a ``BENCH_CHAOS=1``
bench line would (so the two can never be confused with clean
baselines).

The quarantine match is the determinism pin (PR 3's fault-class
discipline extended to the defense): NaN-corrupted live clients are
caught by the finite screen bit-for-bit per the ``(seed, stream,
round)`` schedule, and with ``corrupt_scale_factor`` far above the
benign norm spread, scale-corrupted live clients are exactly the
norm-outlier quarantines.

Run: ``python tools/chaos_smoke.py`` (CPU, seconds — sized for tier-1's
budget; ``tests/test_resilience.py`` drives :func:`run_smoke`
in-process).  Exit code 0 iff every fault class fired, the quarantine
counters match the schedule, and the run completed.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

#: the drill schedule: rates high enough that a short run fires every
#: fault class with probability ~1 (8 clients x N rounds, io fault per
#: checkpoint write attempt), deterministic via the fixed seed.
#: Corruption rates sum to 0.45 so the per-round corrupted fraction
#: stays below the robust estimators' breakdown point for this seed.
CHAOS = {
    "seed": 7,
    "dropout_rate": 0.25,
    "straggler_rate": 0.25,
    "straggler_inflation": 2.0,
    "ckpt_io_error_rate": 0.3,
    "corrupt_nan_rate": 0.15,
    "corrupt_scale_rate": 0.15,
    "corrupt_sign_flip_rate": 0.15,
    # far above any benign norm spread: every scale-corrupted client is
    # a norm outlier, making the quarantine counter schedule-exact
    "corrupt_scale_factor": 100.0,
}

#: the defense under test: finite screen + median-of-norms quarantine
ROBUST = {"screen_nonfinite": True, "norm_multiplier": 4.0,
          "aggregator": "mean"}


def expected_corruption(rounds: int, k_padded: int, n_real: int) -> dict:
    """Replay the seeded schedule host-side: per-class totals over LIVE
    clients (real slot, not chaos-dropped) — what the in-program
    counters and the finite-screen quarantine must equal exactly."""
    import numpy as np

    from msrflute_tpu.resilience.chaos import (CORRUPT_NAN, CORRUPT_SCALE,
                                               CORRUPT_SIGN_FLIP,
                                               ChaosSchedule)

    sched = ChaosSchedule(**{k: v for k, v in CHAOS.items()})
    out = {"nan_injected": 0, "scaled": 0, "sign_flipped": 0}
    shape_only = np.zeros((k_padded, 1, 1), np.float32)
    for r in range(rounds):
        drop, _ = sched.client_faults(r, shape_only)
        mode = sched.corrupt_modes(r, k_padded)
        live = (np.arange(k_padded) < n_real) & (drop == 0)
        out["nan_injected"] += int(((mode == CORRUPT_NAN) & live).sum())
        out["scaled"] += int(((mode == CORRUPT_SCALE) & live).sum())
        out["sign_flipped"] += int(
            ((mode == CORRUPT_SIGN_FLIP) & live).sum())
    return out


def run_smoke(rounds: int = 8, seed: int = 0) -> dict:
    """Run the drill; return the bench-style record (chaos block + fault
    counters + final round).  Raises AssertionError if any fault class
    never fired or the quarantine counters diverge from the seeded
    schedule — the smoke's whole point."""
    from msrflute_tpu.utils.backend import force_cpu_backend
    force_cpu_backend()

    import numpy as np

    from msrflute_tpu.config import FLUTEConfig
    from msrflute_tpu.data import ArraysDataset
    from msrflute_tpu.engine import OptimizationServer
    from msrflute_tpu.models import make_task
    from msrflute_tpu.parallel import make_mesh
    from msrflute_tpu.parallel.mesh import pad_to_mesh

    cfg = FLUTEConfig.from_dict({
        "model_config": {"model_type": "LR", "num_classes": 4,
                         "input_dim": 8},
        "strategy": "fedavg",
        "server_config": {
            "max_iteration": rounds, "num_clients_per_iteration": 6,
            "initial_lr_client": 0.2,
            "optimizer_config": {"type": "sgd", "lr": 1.0},
            "val_freq": 10_000, "initial_val": False,
            "chaos": dict(CHAOS),
            "robust": dict(ROBUST),
            # zero backoff: the injected faults are synthetic; sleeping
            # between retries would only burn the tier-1 budget
            "checkpoint_retry": {"retries": 3, "backoff_base_s": 0.0,
                                 "jitter": 0.0},
            # flutescope on: the smoke also proves injected faults reach
            # the TRACE as structured events, not just the counters
            "telemetry": {"enable": True},
            "data_config": {},
        },
        "client_config": {
            "optimizer_config": {"type": "sgd", "lr": 0.2},
            "data_config": {"train": {"batch_size": 4}}},
    })
    rng = np.random.default_rng(seed)
    users, per = [], []
    for u in range(12):
        users.append(f"u{u:02d}")
        per.append({"x": rng.normal(size=(10, 8)).astype(np.float32),
                    "y": rng.integers(0, 4, 10).astype(np.int32)})
    dataset = ArraysDataset(users, per)

    task = make_task(cfg.model_config)
    with tempfile.TemporaryDirectory() as tmp:
        server = OptimizationServer(task, cfg, dataset, model_dir=tmp,
                                    seed=seed)
        state = server.train()
        counters = {k: float(v) for k, v in server.chaos.counters.items()}
        # ---- flutescope assertion: the injected faults must appear in
        # the trace as structured events (tools/scope's fault table) ----
        import json as _json
        server.scope.close()
        with open(os.path.join(tmp, "telemetry", "trace.json")) as fh:
            trace = _json.load(fh)
        trace_events = {}
        for ev in trace["traceEvents"]:
            if ev.get("ph") == "i":
                trace_events[ev["name"]] = trace_events.get(ev["name"], 0) + 1
        quarantine = {k: float(v)
                      for k, v in server.shield.counters.items()}
        record = {
            "tool": "chaos_smoke",
            "rounds": int(state.round),
            "chaos": server.chaos.describe(),
            "robust": server.shield.describe(),
            "fault_counters": counters,
            "quarantine_counters": quarantine,
            "checkpoint_recovery_events": len(server.ckpt.recovery_events),
            "trace_fault_events": {
                k: v for k, v in sorted(trace_events.items())
                if k in ("chaos_faults", "chaos_corruption",
                         "ckpt_io_fault", "quarantine")},
        }
    assert state.round == rounds, f"run stopped early at {state.round}"
    for key in ("dropped", "straggled", "steps_lost", "ckpt_io_faults",
                "nan_injected", "scaled", "sign_flipped"):
        assert counters[key] > 0, (
            f"fault class {key!r} never fired — the injection path is "
            f"dead ({counters})")
    for name in ("chaos_faults", "chaos_corruption", "ckpt_io_fault",
                 "quarantine"):
        assert record["trace_fault_events"].get(name, 0) > 0, (
            f"fault event {name!r} fired but never reached the trace — "
            f"the telemetry event path is dead ({trace_events})")
    # ---- determinism pin: counters == the seeded injection schedule,
    # replayed host-side from (seed, stream, round) alone ----
    k_padded = pad_to_mesh(
        int(cfg.server_config["num_clients_per_iteration"]), make_mesh())
    expect = expected_corruption(
        rounds, k_padded,
        int(cfg.server_config["num_clients_per_iteration"]))
    for key in ("nan_injected", "scaled", "sign_flipped"):
        assert counters[key] == expect[key], (
            f"corruption counter {key!r}={counters[key]} diverged from "
            f"the seeded schedule ({expect[key]}) — determinism broken")
    assert quarantine["quarantined_nonfinite"] == expect["nan_injected"], (
        "finite-screen quarantine "
        f"({quarantine['quarantined_nonfinite']}) != scheduled NaN "
        f"injections ({expect['nan_injected']})")
    assert quarantine["quarantined_norm_outlier"] == expect["scaled"], (
        "norm-outlier quarantine "
        f"({quarantine['quarantined_norm_outlier']}) != scheduled scale "
        f"corruptions ({expect['scaled']}) — with corrupt_scale_factor "
        "100x the screen must catch exactly the scheduled attackers")
    record["expected_from_schedule"] = expect
    return record


def expected_client_loss(chaos: dict, rounds: int, k_padded: int,
                         n_real: int) -> dict:
    """Replay the seeded schedule host-side for the secagg drill: how
    many LIVE real clients drop (secagg's dropout-recovery cause) and
    how many surviving clients the scale attack poisons (the
    quarantine-recovery cause) per the ``(seed, stream, round)``
    contract — nothing read back from the device."""
    import numpy as np

    from msrflute_tpu.resilience.chaos import CORRUPT_SCALE, ChaosSchedule

    sched = ChaosSchedule(**{k: v for k, v in chaos.items()})
    out = {"dropped": 0, "scaled_live": 0}
    shape_only = np.zeros((k_padded, 1, 1), np.float32)
    for r in range(rounds):
        drop, _ = sched.client_faults(r, shape_only)
        mode = sched.corrupt_modes(r, k_padded)
        real = np.arange(k_padded) < n_real
        out["dropped"] += int((real & (drop > 0)).sum())
        out["scaled_live"] += int(
            ((mode == CORRUPT_SCALE) & real & (drop == 0)).sum())
    return out


def run_secagg_smoke(rounds: int = 6, seed: int = 0) -> dict:
    """The "dropout under the mask" drill (RUNBOOK): secure_agg + seeded
    dropout/stragglers + a 100x scale attack screened by fluteshield's
    submitted-norm vote.  Asserts the per-cause mask-recovery counters
    (``secagg_recovered_dropout`` / ``secagg_recovered_quarantine``)
    EXACTLY match the host-side replay of the fault schedule, and that
    the run ends finite — the masked sum telescoped despite the loss."""
    from msrflute_tpu.utils.backend import force_cpu_backend
    force_cpu_backend()

    import numpy as np

    from msrflute_tpu.config import FLUTEConfig
    from msrflute_tpu.data import ArraysDataset
    from msrflute_tpu.engine import OptimizationServer
    from msrflute_tpu.models import make_task
    from msrflute_tpu.parallel import make_mesh
    from msrflute_tpu.parallel.mesh import pad_to_mesh

    chaos = {"seed": 7, "dropout_rate": 0.25, "straggler_rate": 0.25,
             "straggler_inflation": 2.0, "corrupt_scale_rate": 0.2,
             "corrupt_scale_factor": 100.0}
    k = 6
    cfg = FLUTEConfig.from_dict({
        "model_config": {"model_type": "LR", "num_classes": 4,
                         "input_dim": 8},
        "strategy": "secure_agg",
        "server_config": {
            "max_iteration": rounds, "num_clients_per_iteration": k,
            "initial_lr_client": 0.2,
            "optimizer_config": {"type": "sgd", "lr": 1.0},
            "val_freq": 10_000, "initial_val": False,
            "chaos": dict(chaos),
            "robust": dict(ROBUST),
            "data_config": {},
        },
        "client_config": {
            "optimizer_config": {"type": "sgd", "lr": 0.2},
            "data_config": {"train": {"batch_size": 4}}},
    })
    rng = np.random.default_rng(seed)
    users, per = [], []
    for u in range(12):
        users.append(f"u{u:02d}")
        per.append({"x": rng.normal(size=(10, 8)).astype(np.float32),
                    "y": rng.integers(0, 4, 10).astype(np.int32)})
    dataset = ArraysDataset(users, per)

    task = make_task(cfg.model_config)
    with tempfile.TemporaryDirectory() as tmp:
        server = OptimizationServer(task, cfg, dataset, model_dir=tmp,
                                    seed=seed)
        state = server.train()
        import jax
        from jax.flatten_util import ravel_pytree
        flat = np.asarray(ravel_pytree(jax.device_get(state.params))[0])
        secagg = {kk: float(v)
                  for kk, v in server.strategy.counters.items()}
        quarantine = {kk: float(v)
                      for kk, v in server.shield.counters.items()}
    k_padded = pad_to_mesh(k, make_mesh())
    expect = expected_client_loss(chaos, rounds, k_padded, k)
    assert np.isfinite(flat).all(), (
        "secagg run under chaos ended non-finite — mask recovery or the "
        "submitted-norm screen is broken")
    assert secagg["recovered_dropout"] == expect["dropped"], (
        f"secagg_recovered_dropout={secagg['recovered_dropout']} diverged "
        f"from the seeded dropout schedule ({expect['dropped']}) — the "
        "mask-recovery path is not schedule-exact")
    assert secagg["recovered_quarantine"] == expect["scaled_live"], (
        f"secagg_recovered_quarantine={secagg['recovered_quarantine']} != "
        f"scheduled live scale corruptions ({expect['scaled_live']}) — "
        "with a 100x factor the submitted-norm screen must quarantine "
        "exactly the scheduled attackers")
    assert quarantine["quarantined_norm_outlier"] == expect["scaled_live"]
    return {
        "tool": "chaos_smoke/secagg",
        "rounds": int(state.round),
        "chaos": chaos,
        "secagg": secagg,
        "quarantine_counters": quarantine,
        "expected": expect,
    }


def main() -> int:
    record = run_smoke()
    print(json.dumps(record))
    record_sa = run_secagg_smoke()
    print(json.dumps(record_sa))
    return 0


if __name__ == "__main__":
    sys.exit(main())
