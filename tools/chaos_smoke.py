"""Chaos smoke: an N-round run with dropout + straggler + checkpoint-IO
faults enabled, asserting the injected-fault counters actually moved.

The cheap end-to-end proof that the deterministic fault-injection path
(``server_config.chaos`` -> fused-round fault operands -> packed-stats
counters -> bench contract) is alive: dropout/straggling fold into the
round program, IO faults exercise the checkpoint retry machinery, and
the emitted JSON carries the chaos block + counters exactly like a
``BENCH_CHAOS=1`` bench line would (so the two can never be confused
with clean baselines).

Run: ``python tools/chaos_smoke.py`` (CPU, seconds — sized for tier-1's
budget; ``tests/test_resilience.py`` drives :func:`run_smoke`
in-process).  Exit code 0 iff every fault class fired and the run
completed.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

#: the drill schedule: rates high enough that a short run fires every
#: fault class with probability ~1 (8 clients x N rounds, io fault per
#: checkpoint write attempt), deterministic via the fixed seed
CHAOS = {
    "seed": 7,
    "dropout_rate": 0.25,
    "straggler_rate": 0.25,
    "straggler_inflation": 2.0,
    "ckpt_io_error_rate": 0.3,
}


def run_smoke(rounds: int = 8, seed: int = 0) -> dict:
    """Run the drill; return the bench-style record (chaos block + fault
    counters + final round).  Raises AssertionError if any fault class
    never fired — the smoke's whole point."""
    from msrflute_tpu.utils.backend import force_cpu_backend
    force_cpu_backend()

    import numpy as np

    from msrflute_tpu.config import FLUTEConfig
    from msrflute_tpu.data import ArraysDataset
    from msrflute_tpu.engine import OptimizationServer
    from msrflute_tpu.models import make_task

    cfg = FLUTEConfig.from_dict({
        "model_config": {"model_type": "LR", "num_classes": 4,
                         "input_dim": 8},
        "strategy": "fedavg",
        "server_config": {
            "max_iteration": rounds, "num_clients_per_iteration": 6,
            "initial_lr_client": 0.2,
            "optimizer_config": {"type": "sgd", "lr": 1.0},
            "val_freq": 10_000, "initial_val": False,
            "chaos": dict(CHAOS),
            # zero backoff: the injected faults are synthetic; sleeping
            # between retries would only burn the tier-1 budget
            "checkpoint_retry": {"retries": 3, "backoff_base_s": 0.0,
                                 "jitter": 0.0},
            # flutescope on: the smoke also proves injected faults reach
            # the TRACE as structured events, not just the counters
            "telemetry": {"enable": True},
            "data_config": {},
        },
        "client_config": {
            "optimizer_config": {"type": "sgd", "lr": 0.2},
            "data_config": {"train": {"batch_size": 4}}},
    })
    rng = np.random.default_rng(seed)
    users, per = [], []
    for u in range(12):
        users.append(f"u{u:02d}")
        per.append({"x": rng.normal(size=(10, 8)).astype(np.float32),
                    "y": rng.integers(0, 4, 10).astype(np.int32)})
    dataset = ArraysDataset(users, per)

    task = make_task(cfg.model_config)
    with tempfile.TemporaryDirectory() as tmp:
        server = OptimizationServer(task, cfg, dataset, model_dir=tmp,
                                    seed=seed)
        state = server.train()
        counters = {k: float(v) for k, v in server.chaos.counters.items()}
        # ---- flutescope assertion: the injected faults must appear in
        # the trace as structured events (tools/scope's fault table) ----
        import json as _json
        server.scope.close()
        with open(os.path.join(tmp, "telemetry", "trace.json")) as fh:
            trace = _json.load(fh)
        trace_events = {}
        for ev in trace["traceEvents"]:
            if ev.get("ph") == "i":
                trace_events[ev["name"]] = trace_events.get(ev["name"], 0) + 1
        record = {
            "tool": "chaos_smoke",
            "rounds": int(state.round),
            "chaos": server.chaos.describe(),
            "fault_counters": counters,
            "checkpoint_recovery_events": len(server.ckpt.recovery_events),
            "trace_fault_events": {
                k: v for k, v in sorted(trace_events.items())
                if k in ("chaos_faults", "ckpt_io_fault")},
        }
    assert state.round == rounds, f"run stopped early at {state.round}"
    for key in ("dropped", "straggled", "steps_lost", "ckpt_io_faults"):
        assert counters[key] > 0, (
            f"fault class {key!r} never fired — the injection path is "
            f"dead ({counters})")
    for name in ("chaos_faults", "ckpt_io_fault"):
        assert record["trace_fault_events"].get(name, 0) > 0, (
            f"fault event {name!r} fired but never reached the trace — "
            f"the telemetry event path is dead ({trace_events})")
    return record


def main() -> int:
    record = run_smoke()
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
