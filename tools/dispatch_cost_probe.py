"""Per-dispatch overhead vs argument/result buffer count on the chip.

The faithful (fuse=1) fullrun measured ~88 ms per round DISPATCH for the
LR protocol (``.scratch/fullrun_out/lr_mnist_fuse1`` secsPerRound p50)
against a 0.14 ms trivial-op dispatch floor — suggesting the remote
runtime pays per-BUFFER, not per-call.  This probe times a no-op-ish jit
at varying output-buffer counts and input-tree sizes, with and without
donation, so the engine's stats-packing decision (one flat stats vector
vs a ~15-leaf dict) rests on a measurement.

Fence discipline: every case syncs by fetching ONE scalar from the FIRST
output leaf — a fence whose cost is constant in the buffer count, so the
case timings differ only by what the dispatch itself pays.

Writes one JSON line to stdout.
"""

from __future__ import annotations

import json
import sys
import time


def _sync(out) -> None:
    """Constant-cost fence: fetch one scalar from the first output leaf
    (block_until_ready is not a trustworthy fence on this backend)."""
    import jax
    jax.device_get(jax.tree.leaves(out)[0].ravel()[0])


def _fetch_time(fn, args, iters=30):
    _sync(fn(*args))  # compile + first run
    tic = time.perf_counter()
    for _ in range(iters):
        _sync(fn(*args))
    return (time.perf_counter() - tic) / iters


def main() -> int:
    import jax
    import jax.numpy as jnp

    assert jax.default_backend() == "tpu", jax.default_backend()
    res = {"backend": "tpu", "cases": {}}

    # output-buffer scaling: one [8,128] input, N small outputs
    x = jnp.ones((8, 128), jnp.float32)
    for n_out in (1, 4, 16, 64):
        fn = jax.jit(lambda x, n=n_out: [x[:1, :1] * (i + 1)
                                         for i in range(n)])
        res["cases"][f"outputs_{n_out}"] = round(
            1e3 * _fetch_time(fn, (x,)), 4)

    # input-tree scaling: N small inputs, one output
    for n_in in (1, 4, 16, 64):
        args = [jnp.full((8, 8), float(i)) for i in range(n_in)]
        fn = jax.jit(lambda *a: sum(x[0, 0] for x in a)[None])
        res["cases"][f"inputs_{n_in}"] = round(
            1e3 * _fetch_time(fn, args), 4)

    # host->device staging: the faithful round device_puts ~8-10 small
    # host arrays per round (masks/ids/lrs/rngs) — is each put an RPC?
    import numpy as np
    for n_put in (1, 4, 16):
        host = [np.full((8, 8), float(i), np.float32) for i in range(n_put)]
        # one put call per array (the engine's shape) vs one call on the list
        tic = time.perf_counter()
        for _ in range(30):
            staged = [jax.device_put(h) for h in host]
            _sync(staged)
        res["cases"][f"put_each_{n_put}"] = round(
            1e3 * (time.perf_counter() - tic) / 30, 4)
        tic = time.perf_counter()
        for _ in range(30):
            staged = jax.device_put(host)
            _sync(staged)
        res["cases"][f"put_tree_{n_put}"] = round(
            1e3 * (time.perf_counter() - tic) / 30, 4)

    # donation: does donating a 16-leaf tree change per-dispatch cost?
    # Identical single-leaf fence on both sides; the donated case threads
    # its output back in (the engine's own state-carry pattern).
    tree = [jnp.full((64, 64), float(i)) for i in range(16)]

    def roll(*a):
        return [t + 1.0 for t in a]

    res["cases"]["tree16_no_donate"] = round(
        1e3 * _fetch_time(jax.jit(roll), tuple(tree)), 4)
    fn_don = jax.jit(roll, donate_argnums=tuple(range(16)))
    out = fn_don(*tree)
    _sync(out)
    tic = time.perf_counter()
    iters = 30
    for _ in range(iters):
        out = fn_don(*out)
        _sync(out)
    res["cases"]["tree16_donated_threaded"] = round(
        1e3 * (time.perf_counter() - tic) / iters, 4)

    # input-staging A/B (PR 6, server_config.input_staging): the faithful
    # round's REAL per-dispatch operand mix — [K,S,B,D] feature grid,
    # [K,S,B] sample mask, [K] client mask/ids, [K] chaos drop/
    # keep_steps/corrupt vectors, and the lr/round/threshold scalars —
    # staged per-leaf (the pre-PR shape the ~88 ms suspect came from) vs
    # packed one-buffer-per-dtype through the engine's own packers
    # (utils/flatpack.py AxisPacker/ScalarStager).  This is the number
    # that makes the staging win reproducible on the chip.
    import numpy as _np
    from msrflute_tpu.utils.flatpack import AxisPacker, ScalarStager
    rng = _np.random.default_rng(0)
    K, S, B, D = 10, 4, 20, 64
    axis_tree = {
        "grid": rng.normal(size=(K, S, B, D)).astype(_np.float32),
        "sample_mask": _np.ones((K, S, B), _np.float32),
        "client_mask": _np.ones((K,), _np.float32),
        "client_ids": _np.arange(K, dtype=_np.int32),
        "drop": _np.zeros((K,), _np.float32),
        "keep_steps": _np.full((K,), float(S), _np.float32),
        "corrupt": _np.zeros((K,), _np.int32),
    }
    sc_tree = {"client_lr": _np.float32(0.1),
               "server_lr": _np.float32(1.0),
               "round_idx": _np.int32(0),
               "leakage": _np.float32(_np.inf),
               "quant": _np.float32(-1.0)}
    iters = 30
    # legacy: one device_put per leaf (12 transfers)
    tic = time.perf_counter()
    for _ in range(iters):
        # flint would flag this shape in product code — it IS the probe
        staged = [jax.device_put(v) for v in axis_tree.values()]
        staged += [jax.device_put(v) for v in sc_tree.values()]
        _sync(staged)
    res["cases"]["dispatch_mix_per_leaf"] = round(
        1e3 * (time.perf_counter() - tic) / iters, 4)
    # staged: pack host-side, one put per dtype group (4 transfers)
    ax_packer = AxisPacker(axis_tree, lead_ndim=1)
    stager = ScalarStager(sc_tree)
    tic = time.perf_counter()
    for _ in range(iters):
        ax = jax.device_put(ax_packer.pack_np(axis_tree))
        sc = jax.device_put(stager.pack_np(sc_tree))
        _sync((ax, sc))
    res["cases"]["dispatch_mix_staged"] = round(
        1e3 * (time.perf_counter() - tic) / iters, 4)
    res["staging_speedup"] = round(
        res["cases"]["dispatch_mix_per_leaf"]
        / max(res["cases"]["dispatch_mix_staged"], 1e-9), 2)

    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
