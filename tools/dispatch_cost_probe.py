"""Per-dispatch overhead vs argument/result buffer count on the chip.

The faithful (fuse=1) fullrun measured ~88 ms per round DISPATCH for the
LR protocol (``.scratch/fullrun_out/lr_mnist_fuse1`` secsPerRound p50)
against a 0.14 ms trivial-op dispatch floor — suggesting the remote
runtime pays per-BUFFER, not per-call.  This probe times a no-op-ish jit
at varying output-buffer counts and input-tree sizes, with and without
donation, so the engine's stats-packing decision (one flat stats vector
vs a ~15-leaf dict) rests on a measurement.

Fence discipline: every case syncs by fetching ONE scalar from the FIRST
output leaf — a fence whose cost is constant in the buffer count, so the
case timings differ only by what the dispatch itself pays.

Writes one JSON line to stdout.
"""

from __future__ import annotations

import json
import sys
import time


def _sync(out) -> None:
    """Constant-cost fence: fetch one scalar from the first output leaf
    (block_until_ready is not a trustworthy fence on this backend)."""
    import jax
    jax.device_get(jax.tree.leaves(out)[0].ravel()[0])


def _fetch_time(fn, args, iters=30):
    _sync(fn(*args))  # compile + first run
    tic = time.perf_counter()
    for _ in range(iters):
        _sync(fn(*args))
    return (time.perf_counter() - tic) / iters


def main() -> int:
    import jax
    import jax.numpy as jnp

    assert jax.default_backend() == "tpu", jax.default_backend()
    res = {"backend": "tpu", "cases": {}}

    # output-buffer scaling: one [8,128] input, N small outputs
    x = jnp.ones((8, 128), jnp.float32)
    for n_out in (1, 4, 16, 64):
        fn = jax.jit(lambda x, n=n_out: [x[:1, :1] * (i + 1)
                                         for i in range(n)])
        res["cases"][f"outputs_{n_out}"] = round(
            1e3 * _fetch_time(fn, (x,)), 4)

    # input-tree scaling: N small inputs, one output
    for n_in in (1, 4, 16, 64):
        args = [jnp.full((8, 8), float(i)) for i in range(n_in)]
        fn = jax.jit(lambda *a: sum(x[0, 0] for x in a)[None])
        res["cases"][f"inputs_{n_in}"] = round(
            1e3 * _fetch_time(fn, args), 4)

    # host->device staging: the faithful round device_puts ~8-10 small
    # host arrays per round (masks/ids/lrs/rngs) — is each put an RPC?
    import numpy as np
    for n_put in (1, 4, 16):
        host = [np.full((8, 8), float(i), np.float32) for i in range(n_put)]
        # one put call per array (the engine's shape) vs one call on the list
        tic = time.perf_counter()
        for _ in range(30):
            staged = [jax.device_put(h) for h in host]
            _sync(staged)
        res["cases"][f"put_each_{n_put}"] = round(
            1e3 * (time.perf_counter() - tic) / 30, 4)
        tic = time.perf_counter()
        for _ in range(30):
            staged = jax.device_put(host)
            _sync(staged)
        res["cases"][f"put_tree_{n_put}"] = round(
            1e3 * (time.perf_counter() - tic) / 30, 4)

    # donation: does donating a 16-leaf tree change per-dispatch cost?
    # Identical single-leaf fence on both sides; the donated case threads
    # its output back in (the engine's own state-carry pattern).
    tree = [jnp.full((64, 64), float(i)) for i in range(16)]

    def roll(*a):
        return [t + 1.0 for t in a]

    res["cases"]["tree16_no_donate"] = round(
        1e3 * _fetch_time(jax.jit(roll), tuple(tree)), 4)
    fn_don = jax.jit(roll, donate_argnums=tuple(range(16)))
    out = fn_don(*tree)
    _sync(out)
    tic = time.perf_counter()
    iters = 30
    for _ in range(iters):
        out = fn_don(*out)
        _sync(out)
    res["cases"]["tree16_donated_threaded"] = round(
        1e3 * (time.perf_counter() - tic) / iters, 4)

    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
