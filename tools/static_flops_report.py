"""Static per-op FLOP decomposition for every benchmark protocol model.

Writes PROFILE_STATIC.json: for each ``bench.build_protocols`` protocol
(the TPU geometries, incl. mlm_bert), the exact
conv/dot/elementwise/other FLOP split of one client grad step — the
round's inner loop — from the jaxpr (``msrflute_tpu/utils/flops.py``).
Configs and batches come from bench.py itself (same path
``tools/profile_round.py`` uses), so the report cannot drift from what
the benchmark actually runs.  Chip-independent: this is the half of the
compute-bound argument that needs no TPU — it shows the benchmark
rounds are MXU work (conv+dot), not bookkeeping.  The on-chip half
(wall-clock, MFU, pack_share) is ``tools/profile_round.py``.

Usage: python tools/static_flops_report.py [--out PROFILE_STATIC.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PROFILE_STATIC.json"))
    args = ap.parse_args()

    import bench  # repo-root harness: the protocol table of record

    import jax

    from msrflute_tpu.data.batching import steps_for
    from msrflute_tpu.models import make_task
    from msrflute_tpu.utils.flops import flops_by_op

    # the TPU protocol geometries are the benchmark; building them off-TPU
    # only affects dataset size, not the per-step shapes we analyze
    protocols = bench.build_protocols(True, np.random.default_rng(0))

    report = {}
    for name, spec in protocols.items():
        cfg, dataset = spec["cfg"], spec["data"]()
        task = make_task(cfg.model_config)
        params = task.init_params(jax.random.PRNGKey(0))
        bs = int(cfg.client_config.data_config.train["batch_size"])
        max_steps = steps_for(int(max(dataset.num_samples)), bs,
                              cfg.client_config.get("desired_max_samples"))
        # _one_client_batch already yields one step's [B, ...] arrays
        batch = bench._one_client_batch(dataset, bs, max_steps)

        def grad_step(p, _batch=batch, _task=task):
            return jax.grad(lambda pp: _task.loss(
                pp, _batch, jax.random.PRNGKey(0), True)[0])(p)

        res = flops_by_op(grad_step, params)
        report[name] = {
            "batch_shape": list(np.shape(batch["x"])),
            "total_flops": res["total"],
            "mxu_share": res["mxu_share"],
            "conv_share": res["conv_share"],
            "dot_share": res["dot_share"],
            "elementwise_share": res["elementwise_share"],
            "other_share": res["other_share"],
            "approximate": res["approximate"],
        }
        # XLA's own compiled-program numbers next to the jaxpr walk —
        # through the ONE shared helper (telemetry/xla.aot_cost, same
        # path as bench.grad_step_cost and the live device-truth layer),
        # so the two FLOP accountings can be compared without wondering
        # whether they were measured differently
        from msrflute_tpu.telemetry.xla import aot_cost
        cost = aot_cost(grad_step, params)
        if cost is not None:
            report[name]["xla_flops"] = cost.get("flops")
            report[name]["xla_bytes_accessed"] = cost.get("bytes_accessed")
            report[name]["xla_hbm_bytes"] = cost.get("hbm_bytes")
        print(f"{name}: mxu={res['mxu_share']:.3f} "
              f"(conv={res['conv_share']:.3f} dot={res['dot_share']:.3f})")

    with open(args.out, "w") as fh:
        json.dump({"note": "exact per-op FLOP split of one client grad "
                           "step per bench.build_protocols protocol "
                           "(utils/flops.py jaxpr walk; geometries taken "
                           "from bench.py itself); chip-independent "
                           "compute-bound evidence — wall-clock/MFU live "
                           "in the bench/profile artifacts",
                   "protocols": report}, fh, indent=1)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
