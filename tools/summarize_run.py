"""Run summary from a training run's metrics stream.

The reference surfaces run metrics through AzureML dashboards
(``run.log`` calls throughout ``core/server.py``); this build streams the
same scalars to ``<out>/log/metrics.jsonl``.  This tool is the offline
dashboard: per-metric last/best/count plus the timing summary.

Usage:
    python tools/summarize_run.py <outputPath>   # or the log dir itself
"""

from __future__ import annotations

import json
import os
import sys
from collections import OrderedDict


def load_metrics(path: str):
    """Locate and parse metrics.jsonl under a run dir (or take it directly)."""
    candidates = [path,
                  os.path.join(path, "metrics.jsonl"),
                  os.path.join(path, "log", "metrics.jsonl")]
    for cand in candidates:
        if os.path.isfile(cand):
            records = []
            with open(cand) as fh:
                for line in fh:
                    if not line.strip():
                        continue
                    try:
                        records.append(json.loads(line))
                    except json.JSONDecodeError:
                        # a run killed mid-write leaves a truncated tail
                        # line; the summary matters most for exactly that
                        # crashed run, so skip instead of dying
                        continue
            return records
    raise FileNotFoundError(f"no metrics.jsonl under {path!r}")


def summarize(records):
    """Per-metric summary rows: last/min/max/count + last step."""
    out: "OrderedDict[str, dict]" = OrderedDict()
    for rec in records:
        name = rec.get("name")
        value = rec.get("value")
        if name is None or isinstance(value, bool) or \
                not isinstance(value, (int, float)):
            continue
        row = out.setdefault(name, {"n": 0, "last": None, "step": None,
                                    "min": float("inf"),
                                    "max": float("-inf")})
        row["n"] += 1
        row["last"] = value
        row["step"] = rec.get("step")
        row["min"] = min(row["min"], value)
        row["max"] = max(row["max"], value)
    return out


def main() -> None:
    if len(sys.argv) != 2:
        raise SystemExit(__doc__)
    records = load_metrics(sys.argv[1])
    rows = summarize(records)
    if not rows:
        print("no scalar metrics found")
        return
    w = max(len(n) for n in rows) + 2
    print(f"{'metric':<{w}} {'last':>12} {'min':>12} {'max':>12} "
          f"{'n':>5} {'step':>6}")
    for name, r in rows.items():
        step = "-" if r["step"] is None else str(r["step"])
        print(f"{name:<{w}} {r['last']:>12.6g} {r['min']:>12.6g} "
              f"{r['max']:>12.6g} {r['n']:>5} {step:>6}")


if __name__ == "__main__":
    main()
